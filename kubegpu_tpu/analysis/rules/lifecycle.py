"""Resource-lifecycle: ``acquired -> released`` on all paths.

The streaming transport brought the package its densest collection of
OS-level resources yet — sockets, pump/writer threads, subscriber
registrations, WAL file handles — and every future controller (defrag
rebalancer, autoscaler) adds more. This rule is the typestate that
keeps them honest: a resource acquired in a function must be released,
handed off, or daemon-exempt on **every** path out of it, exception
edges included, using the same CFG/obligation engine as charge-pairing
(:mod:`kubegpu_tpu.analysis.dataflow`).

Tracked resource kinds and their release obligations:

===============  =======================================  ==============
kind             acquired by                              released by
===============  =======================================  ==============
socket           ``socket.socket`` /                      ``.close()`` /
                 ``socket.create_connection``             ``.detach()``
thread           ``threading.Thread(...)`` then           ``.join()``
                 ``.start()`` (``daemon=True`` exempt)
file             ``open(...)`` / ``os.fdopen(...)``       ``.close()``
subscriber       ``*.add_stream_subscriber(...)``         ``.stop()``
lease loop       ``Elector``/``ShardCoordinator``         ``.stop()`` /
                 then ``.start()``                        ``.release()``
===============  =======================================  ==============

**Escapes discharge the obligation.** Passing the resource to any call
(``self._conns.add(conn)``, ``remove_stream_subscriber(sub)``,
``cls(sock)``), storing it (``self._fh = fh``, ``y = x``, a container
literal), returning or yielding it — all transfer ownership somewhere
this function-local analysis cannot see, and the rule goes silent
rather than noisy. ``with`` context managers are release-on-exit by
construction and never tracked. A bound name that escapes anywhere
*before* a thread/elector ``.start()`` gate is owned elsewhere and not
tracked either.

Path semantics mirror charge-pairing's contract: normal exits and
explicit ``raise`` exits are checked; each ``except`` handler covering
the acquisition must release on its own paths; implicit propagation
out of the function is the interpreter/GC backstop and is not flagged;
loops use may-iterate semantics with the canonical-cleanup refinement.
Deliberate leaks carry ``# analysis: disable=resource-lifecycle`` with
a justification the suppression audit keeps honest.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, List, Optional, Tuple

from kubegpu_tpu.analysis.dataflow import (ControlFlowGraph, Node, build_cfg,
                                           may_leak)
from kubegpu_tpu.analysis.engine import (Context, Finding, SourceFile,
                                         dotted_name)


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    kind: str
    # fully-dotted constructor names (matched against the call's dotted
    # name, or its last component for bare/attribute calls)
    ctors: frozenset
    releases: frozenset        # receiver methods that discharge
    what: str                  # human phrase for findings
    gate: Optional[str] = None  # obligation starts at x.<gate>() if set
    daemon_kwarg: Optional[str] = None  # ctor kwarg that exempts


SPECS: Tuple[ResourceSpec, ...] = (
    ResourceSpec("socket",
                 frozenset({"socket.socket", "socket.create_connection"}),
                 frozenset({"close", "detach"}),
                 "socket is never closed"),
    ResourceSpec("thread",
                 frozenset({"threading.Thread", "Thread"}),
                 frozenset({"join"}),
                 "non-daemon thread is started but never joined",
                 gate="start", daemon_kwarg="daemon"),
    ResourceSpec("file",
                 frozenset({"open", "os.fdopen", "io.open"}),
                 frozenset({"close"}),
                 "file handle is never closed"),
    ResourceSpec("subscriber",
                 frozenset({"add_stream_subscriber"}),
                 frozenset({"stop"}),
                 "stream subscriber is registered but never severed"),
    ResourceSpec("lease loop",
                 frozenset({"Elector", "ShardCoordinator"}),
                 frozenset({"stop", "release"}),
                 "lease/election loop is started but never stopped",
                 gate="start"),
)


def _ctor_spec(call: ast.AST) -> Optional[ResourceSpec]:
    """The spec whose constructor this call invokes, if any."""
    if not isinstance(call, ast.Call):
        return None
    dotted = dotted_name(call.func)
    last = None
    if isinstance(call.func, ast.Attribute):
        last = call.func.attr
    elif isinstance(call.func, ast.Name):
        last = call.func.id
    for spec in SPECS:
        for ctor in spec.ctors:
            if dotted == ctor:
                return spec
            if "." not in ctor and last == ctor and \
                    (dotted is None or dotted == ctor or
                     dotted.endswith("." + ctor)):
                return spec
            if "." in ctor and dotted is not None and \
                    dotted.endswith("." + ctor):
                return spec
    return None


def _is_daemon_exempt(call: ast.Call, spec: ResourceSpec) -> bool:
    if spec.daemon_kwarg is None:
        return False
    for kw in call.keywords:
        if kw.arg == spec.daemon_kwarg:
            # daemon=True (or any non-constant expression: give the
            # benefit of the doubt — err toward silence)
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True
    return False


@dataclasses.dataclass
class _Binding:
    """One ``x = <ctor>(...)`` the rule tracks through the function."""

    name: str
    spec: ResourceSpec
    acquire_stmt: ast.stmt   # the binding statement
    site_stmt: ast.stmt      # where the obligation starts (gate or bind)


class _NameUse:
    """Classification of one occurrence of the tracked name."""

    READ = "read"        # receiver/test/interpolation use: still held
    RELEASE = "release"  # x.close()/x.join()/... discharges
    ESCAPE = "escape"    # ownership left this function's hands
    EXEMPT = "exempt"    # x.daemon = True before start


def _classify_uses(root: ast.AST, name: str,
                   spec: ResourceSpec) -> List[str]:
    """Every occurrence of ``name`` under ``root``, classified. Parent
    chains decide: a receiver use (``x.sendall(...)``), a guard
    (``if x is None``), or an f-string repr keeps holding the
    resource; appearing as a call argument, in a container literal, as
    an assignment's value, or in a ``return``/``yield`` escapes it."""
    parents: dict = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    out: List[str] = []
    for node in ast.walk(root):
        if not (isinstance(node, ast.Name) and node.id == name):
            continue
        out.append(_classify_one(node, parents, spec))
    return out


def _classify_one(node: ast.AST, parents: dict,
                  spec: ResourceSpec) -> str:
    parent = parents.get(id(node))
    if isinstance(parent, ast.Attribute) and parent.value is node:
        grand = parents.get(id(parent))
        # x.<release>() discharges; x.daemon = True exempts a thread
        if isinstance(grand, ast.Call) and grand.func is parent:
            if parent.attr in spec.releases:
                return _NameUse.RELEASE
            return _NameUse.READ  # x.sendall(...), x.fileno(), ...
        if spec.daemon_kwarg is not None and \
                parent.attr == spec.daemon_kwarg and \
                isinstance(grand, ast.Assign) and parent in grand.targets:
            return _NameUse.EXEMPT
        return _NameUse.READ  # attribute read, or x.attr = v mutation
    if isinstance(parent, ast.Subscript) and parent.value is node:
        return _NameUse.READ  # x[i]
    if isinstance(parent, (ast.Compare, ast.BoolOp, ast.UnaryOp,
                           ast.FormattedValue, ast.JoinedStr)):
        return _NameUse.READ  # guards and reprs hold, not leak
    if isinstance(parent, ast.Assign) and node in parent.targets:
        return _NameUse.RELEASE  # rebinding drops our tracking
    if isinstance(parent, ast.withitem):
        return _NameUse.RELEASE  # context manager releases on exit
    if isinstance(parent, ast.Delete):
        return _NameUse.RELEASE
    if isinstance(parent, (ast.If, ast.While)) and \
            getattr(parent, "test", None) is node:
        return _NameUse.READ
    # call argument, container element, assignment value, return/yield
    # value, comprehension, starred, await... — ownership moved on
    return _NameUse.ESCAPE


class ResourceLifecycle:
    """Sockets, threads, file handles, stream subscribers, and lease
    loops acquired by a function must be released (or handed off) on
    every path out of it — exception edges included."""

    name = "resource-lifecycle"
    description = ("package-created sockets/threads/files/stream "
                   "subscribers/lease loops must reach their release "
                   "(close/join/stop) on all paths, exception edges "
                   "included; hand-offs and daemon threads are exempt")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        for src in sources:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_function(src, node)

    # -- per-function analysis ------------------------------------------------

    def _check_function(self, src: SourceFile,
                        fn: ast.AST) -> Iterator[Finding]:
        bindings = self._collect_bindings(fn)
        dropped = self._dropped_acquires(fn)
        if not bindings and not dropped:
            return
        cfg = build_cfg(fn)
        for stmt, spec in dropped:
            yield Finding(
                self.name, src.path, stmt.lineno,
                f"{spec.what}: the {spec.kind} is acquired and its only "
                f"reference immediately dropped — bind it and release "
                f"it, or hand it off")
        for binding in bindings:
            yield from self._check_binding(src, fn, cfg, binding)

    def _collect_bindings(self, fn: ast.AST) -> List[_Binding]:
        """``x = <ctor>(...)`` statements directly in this function
        (nested defs are their own unit), with gated kinds anchored at
        their ``x.start()`` statement."""
        out: List[_Binding] = []
        for stmt in self._own_statements(fn):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            value = stmt.value
            if value is None:
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                continue  # attribute/tuple targets escape immediately
            spec = _ctor_spec(value)
            if spec is None:
                continue
            assert isinstance(value, ast.Call)
            if _is_daemon_exempt(value, spec):
                continue
            name = targets[0].id
            site = stmt
            if spec.gate is not None:
                site_or_none = self._gate_stmt(fn, stmt, name, spec)
                if site_or_none is None:
                    continue  # never started, or owned elsewhere first
                site = site_or_none
            out.append(_Binding(name, spec, stmt, site))
        return out

    def _own_statements(self, fn: ast.AST) -> Iterator[ast.stmt]:
        """Every statement in this function, not descending into
        nested function/class definitions."""
        work: List[ast.stmt] = list(getattr(fn, "body", []))
        while work:
            stmt = work.pop()
            yield stmt
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    work.append(child)
                else:
                    work.extend(c for c in ast.iter_child_nodes(child)
                                if isinstance(c, ast.stmt))
        return

    def _gate_stmt(self, fn: ast.AST, bind_stmt: ast.stmt, name: str,
                   spec: ResourceSpec) -> Optional[ast.stmt]:
        """The ``x.start()`` statement that opens a gated obligation,
        or None when the resource never starts here — or escapes (or
        is daemon-exempted) before starting, i.e. is owned elsewhere."""
        gate: Optional[ast.stmt] = None
        for stmt in self._simple_statements(fn):
            if stmt is bind_stmt:
                continue
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == spec.gate and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == name:
                    if gate is None or stmt.lineno < gate.lineno:
                        gate = stmt
        if gate is None:
            return None
        for stmt in self._simple_statements(fn):
            if stmt is bind_stmt or stmt.lineno >= gate.lineno:
                continue
            uses = _classify_uses(stmt, name, spec)
            if _NameUse.EXEMPT in uses:
                return None
            if _NameUse.ESCAPE in uses or _NameUse.RELEASE in uses:
                return None  # stored/handed off before start
        return gate

    def _simple_statements(self, fn: ast.AST) -> Iterator[ast.stmt]:
        """Non-compound statements only: a compound statement's header
        must not soak up matches that belong to its nested children
        (which this walk yields in their own right)."""
        for stmt in self._own_statements(fn):
            if not isinstance(stmt, (ast.If, ast.For, ast.AsyncFor,
                                     ast.While, ast.Try, ast.With,
                                     ast.AsyncWith)):
                yield stmt

    def _dropped_acquires(self, fn: ast.AST) \
            -> List[Tuple[ast.stmt, ResourceSpec]]:
        """Bare ``Expr`` statements that acquire and drop the result:
        ``socket.create_connection(...)`` on its own line, or a
        ``Thread(...).start()`` chain without ``daemon=True``."""
        out: List[Tuple[ast.stmt, ResourceSpec]] = []
        for stmt in self._own_statements(fn):
            if not isinstance(stmt, ast.Expr):
                continue
            value = stmt.value
            spec = _ctor_spec(value)
            if spec is not None and spec.gate is None:
                assert isinstance(value, ast.Call)
                if not _is_daemon_exempt(value, spec):
                    out.append((stmt, spec))
                continue
            # Thread(...).start() / Elector(...).start() chains
            if isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Attribute):
                inner = value.func.value
                spec = _ctor_spec(inner)
                if spec is not None and spec.gate == value.func.attr:
                    assert isinstance(inner, ast.Call)
                    if not _is_daemon_exempt(inner, spec):
                        out.append((stmt, spec))
        return out

    def _check_binding(self, src: SourceFile, fn: ast.AST,
                       cfg: ControlFlowGraph,
                       binding: _Binding) -> Iterator[Finding]:
        site = cfg.node_for(binding.site_stmt)
        if site is None:
            return  # e.g. statically unreachable code

        def releases(node: Node) -> bool:
            # A None-guarded cleanup — `if sub is not None:
            # remove(sub)` — is credited at the guard: on the branch
            # that skips the body the resource was never acquired (the
            # guard exists precisely to encode that), so a plain join
            # would manufacture a phantom leak.
            if isinstance(node.stmt, ast.If) and \
                    binding.name in {n.id for n in ast.walk(node.stmt.test)
                                     if isinstance(n, ast.Name)}:
                for body_stmt in node.stmt.body:
                    uses = _classify_uses(body_stmt, binding.name,
                                          binding.spec)
                    if _NameUse.RELEASE in uses or _NameUse.ESCAPE in uses:
                        return True
            for sub in node.effect_asts():
                uses = _classify_uses(sub, binding.name, binding.spec)
                if _NameUse.RELEASE in uses or _NameUse.ESCAPE in uses \
                        or _NameUse.EXEMPT in uses:
                    return True
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    # re-binding x (even to another acquire) drops this
                    # obligation; the new acquire is its own site
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        if isinstance(t, ast.Name) and \
                                t.id == binding.name:
                            return True
            return False

        site_releases = False
        if binding.site_stmt is not binding.acquire_stmt:
            # the gate statement itself may hand off (rare)
            uses = _classify_uses(binding.site_stmt, binding.name,
                                  binding.spec)
            site_releases = _NameUse.ESCAPE in uses
        # site_raise_holds=False: if `x = open(...)` raises, nothing
        # was bound, so a handler covering only the acquisition itself
        # owes no release
        report = may_leak(cfg, site, releases, site_releases=site_releases,
                          site_raise_holds=False)
        spec = binding.spec
        line = binding.site_stmt.lineno
        if report.normal:
            yield Finding(
                self.name, src.path, line,
                f"{spec.what}: a path from here to function exit "
                f"reaches no {'/'.join(sorted(spec.releases))} of "
                f"`{binding.name}` and never hands it off")
        for handler in report.handlers:
            yield Finding(
                self.name, src.path, handler.lineno,
                f"exception edge leaks the {spec.kind}: this handler "
                f"covers the acquisition of `{binding.name}` but no "
                f"path through it releases or hands it off")

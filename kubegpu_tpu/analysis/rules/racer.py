"""Static race detection + hot-path purity budget.

``racer`` is the RacerD-style interprocedural lockset pass (Blackshear
et al., Infer): discover every concurrency root in the repo, attribute
every shared-field write site the lockset held there (flow-sensitive
locally, meet-over-call-sites interprocedurally — see
``analysis/locksets.py``), and report any field written from two or
more roots whose write-site locksets share no common lock. Unlike the
flat ``lock-discipline`` rule (which trusts a field written under a
lock to define its own guard), this pass needs no training write: an
*entirely* unguarded counter bumped from two threads is exactly what it
exists to catch. Intentionally lock-free state is declared, not
waived: ``# guarded-by: self._lock`` (protection the analysis cannot
see — join-before-read hand-offs, protocol serialization) or
``# racer: single-writer`` (one thread owns all writes), both bound to
the field and themselves checked for referring to a real lock.

``hot-path`` is the vectorization-readiness budget for ROADMAP item 1:
the functions reachable from the scheduler's filter→score→allocate
loop are the code that must become pure array operations, so the rule
(1) inventories every *blocker* in that closure — lock acquisitions,
I/O and logging calls, and per-call allocation counts over budget —
into a ranked report (``python -m kubegpu_tpu.analysis --rule hot-path
--report``), and (2) enforces the ratchet: a function annotated
``# hot-path: pure`` (optionally ``alloc=N``) is CONTRACTED clean, and
any blocker inside it is a finding. The report is the worklist the
vectorized-core refactor burns down; the annotations pin each function
it converts so the purity can never silently regress.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from kubegpu_tpu.analysis import locksets
from kubegpu_tpu.analysis.dataflow import CallGraph
from kubegpu_tpu.analysis.engine import (Context, Finding, SourceFile,
                                         bound_comments, dotted_name)
from kubegpu_tpu.analysis.locksets import (Access, FieldKey, LocksetModel,
                                           field_write_sites, shared_model)


class Racer:
    """Interprocedural lockset race detector: a field written from ≥ 2
    concurrency roots must have a non-empty intersection of write-site
    locksets, a field-level ``# guarded-by:``/``# racer: single-writer``
    declaration, or it is a report."""

    name = "racer"
    description = ("fields written from >=2 thread roots must share a "
                   "common lock across all write sites (or carry a "
                   "checked `# guarded-by:` / `# racer: single-writer` "
                   "declaration)")

    # The workload half (training/serving JAX code) is single-threaded
    # host-loop code driven by one caller; its method names (`submit`,
    # `step`, `run`) collide with the control plane's thread roots under
    # name-based resolution, so its fields are out of this rule's scope
    # — the control plane (scheduler, cluster, node, obs, analysis) is
    # where the 16-worker pool, HA replicas, and stream fan-out live.
    # Scoped at query time so the model itself is shared with hot-path.
    SKIP_TREES = ("workload",)

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        model = shared_model(ctx, sources)
        skip = {s.path for s in sources
                if s.relparts and s.relparts[0] in self.SKIP_TREES}
        reach = model.roots_reaching()
        yield from self._check_guard_notes(model, skip)
        for field, sites in sorted(
                field_write_sites(model).items(),
                key=lambda kv: (kv[1][0].path, kv[1][0].line)):
            sites = [acc for acc in sites if acc.path not in skip]
            if sites:
                yield from self._check_field(model, reach, field, sites)

    # -- per-field race check -------------------------------------------------

    def _check_field(self, model: LocksetModel, reach: Dict[str, Set[str]],
                     field: FieldKey,
                     sites: List[Access]) -> Iterator[Finding]:
        roots: Set[str] = set()
        rooted_sites: List[Access] = []
        for acc in sites:
            acc_roots = reach.get(acc.func)
            if acc_roots:
                roots |= acc_roots
                rooted_sites.append(acc)
        concurrency = sum(model.root_multiplicity(r) for r in roots)
        if concurrency < 2 or not rooted_sites:
            return
        locksets_held = [model.effective_locks(acc) for acc in rooted_sites]
        common: FrozenSet[str] = locksets_held[0]
        for held in locksets_held[1:]:
            common = common & held
        if common:
            return  # consistently guarded
        if field in model.guards:
            # declared lock-free discipline; a guarded-by naming a
            # nonexistent lock is _check_guard_notes's finding
            return
        bare = [acc for acc, held in zip(rooted_sites, locksets_held)
                if not held] or rooted_sites
        first = min(bare, key=lambda a: (a.path, a.line))
        held_somewhere = sorted(set().union(*locksets_held))
        hint = (f"; other write sites hold {', '.join(held_somewhere)} — "
                f"acquire it here too or annotate the field "
                f"`# guarded-by: {held_somewhere[0]}`") if held_somewhere \
            else ("; add a lock, or declare the discipline with "
                  "`# guarded-by: <lock>` / `# racer: single-writer`")
        yield Finding(
            self.name, first.path, first.line,
            f"{field.render()} is written from {len(roots)} concurrency "
            f"root(s) ({locksets.describe_roots(roots, model)}) with no "
            f"common lock across its write sites{hint}")

    @staticmethod
    def _lock_exists(model: LocksetModel, field: FieldKey,
                     lock: str) -> bool:
        """Three accepted spellings: ``self._lock`` (a lock attribute of
        the field's own class), ``SomeClass._lock`` (a *monitor* member:
        the field holds an instance of a class that guards itself
        internally — ``self.queue`` behind ``SchedulingQueue._lock``),
        or a bare module-level lock name."""
        if lock.startswith("self."):
            attr = lock.split(".", 1)[1]
            if field.owner.startswith("<"):
                return False
            return attr in model.class_locks.get(field.owner, set())
        if "." in lock:
            cls, attr = lock.rsplit(".", 1)
            if cls in model.class_locks:
                return attr in model.class_locks[cls]
        name = lock.split(".")[-1]
        return any(name in names for names in model.module_locks.values())

    def _check_guard_notes(self, model: LocksetModel,
                           skip: set) -> Iterator[Finding]:
        """guarded-by annotations on fields that never race still must
        name a real lock — a typo'd declaration is worse than none."""
        for field, note in sorted(model.guards.items(),
                                  key=lambda kv: (kv[1].path, kv[1].line)):
            if note.path not in skip and \
                    note.kind == "guarded-by" and note.lock is not None and \
                    not self._lock_exists(model, field, note.lock):
                yield Finding(
                    self.name, note.path, note.line,
                    f"`# guarded-by: {note.lock}` on {field.render()} "
                    f"names a lock the owner does not define; fix the "
                    f"annotation or declare the lock")


# ---- hot-path purity budget -------------------------------------------------

# The filter -> score -> allocate loop's entry points in scheduler/core.py
# (name-matched so the fixture trees can model the same shape).
HOT_ROOTS = ("find_nodes_that_fit", "prioritize_nodes", "allocate_devices")

DEFAULT_ALLOC_BUDGET = 8

PURE_RE = re.compile(r"#\s*hot-path:\s*pure(?:\s+alloc=(?P<alloc>\d+))?")

# Calls that are I/O or logging — per-pod-per-node work must never pay
# a syscall or a formatting round trip (and a log call allocates too).
_IO_CALL_HEADS = frozenset({"open", "print", "input"})
_IO_RECEIVERS = frozenset({"log", "logger", "logging", "warnings", "sys",
                           "os", "socket", "subprocess", "requests",
                           "urllib", "time"})
_IO_TIME_OK = frozenset({"monotonic", "perf_counter", "time", "time_ns",
                         "perf_counter_ns", "monotonic_ns"})

_ALLOC_CALL_NAMES = frozenset({"list", "dict", "set", "tuple", "sorted",
                               "frozenset", "deepcopy", "copy", "dumps",
                               "loads", "deque"})

# Names too generic to follow when expanding the hot-path closure: a
# `feasible.pop(...)` or `spool.append(...)` is a container operation,
# not a call into the same-named package method — following it would
# pull `SchedulingQueue.pop` or `WriteAheadLog.append` into the closure
# by name collision alone.
_GENERIC_NAMES = frozenset({
    "add", "append", "clear", "close", "copy", "count", "discard",
    "extend", "flush", "get", "index", "insert", "items", "join", "keys",
    "pop", "popleft", "put", "read", "recv", "release", "remove", "send",
    "set", "setdefault", "sort", "split", "start", "stop", "update",
    "values", "wait", "write",
})


class _Blockers:
    """Per-function blocker inventory."""

    def __init__(self) -> None:
        self.locks: List[Tuple[str, int]] = []   # (token, line)
        self.io: List[Tuple[str, int]] = []      # (label, line)
        self.allocs: int = 0

    def severity(self) -> Tuple[int, int, int]:
        return (len(self.locks), len(self.io), self.allocs)

    def any(self, budget: int) -> bool:
        return bool(self.locks or self.io or self.allocs > budget)


def _scan_blockers(fn: ast.AST, model: LocksetModel,
                   qualname: str) -> _Blockers:
    out = _Blockers()
    for acq in model.acquisitions:
        if acq.func == qualname:
            out.locks.append((acq.token, acq.line))
    for node in _own_body_walk(fn):
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp, ast.List, ast.Dict, ast.Set,
                             ast.JoinedStr)):
            out.allocs += 1
        elif isinstance(node, ast.Call):
            label = _io_label(node)
            if label is not None:
                out.io.append((label, node.lineno))
            elif _is_alloc_call(node):
                out.allocs += 1
    out.locks.sort(key=lambda t: t[1])
    out.io.sort(key=lambda t: t[1])
    return out


def _own_body_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """ast.walk, but nested function/class definitions are opaque (they
    run on someone else's schedule and carry their own entry)."""
    work: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while work:
        node = work.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        work.extend(ast.iter_child_nodes(node))


def _io_label(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Name) and func.id in _IO_CALL_HEADS:
        return f"{func.id}()"
    dotted = dotted_name(func)
    if dotted is None:
        return None
    head = dotted.split(".")[0]
    if head in _IO_RECEIVERS:
        if head == "time" and dotted.split(".")[-1] in _IO_TIME_OK:
            return None  # clock reads are cheap and everywhere
        return f"{dotted}()"
    if dotted.endswith(".wait") or dotted.endswith(".sleep"):
        return f"{dotted}()"
    return None


def _is_alloc_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in _ALLOC_CALL_NAMES
    if isinstance(func, ast.Attribute):
        return func.attr in _ALLOC_CALL_NAMES
    return False


def _pure_marks(src: SourceFile) -> Dict[int, int]:
    """def-line -> allocation budget for every ``# hot-path: pure``
    comment in the file, via the shared def-bound comment walk (the
    twin-of and guard declarations stack with the contract, and a
    stacked comment must not silently unbind it)."""
    out: Dict[int, int] = {}
    for _cline, dline, m in bound_comments(src, PURE_RE):
        if dline is not None:
            out[dline] = int(m.group("alloc") or DEFAULT_ALLOC_BUDGET)
    return out


class HotPathPurity:
    """The vectorization-readiness ratchet: blockers (locks, I/O,
    logging, allocation storms) in the filter→score→allocate closure are
    inventoried into a ranked report, and any function contracted
    ``# hot-path: pure`` containing one is a finding."""

    name = "hot-path"
    description = ("functions on the filter->score->allocate hot path "
                   "annotated `# hot-path: pure` must acquire no locks, "
                   "do no I/O or logging, and stay under the per-call "
                   "allocation budget; --report ranks every blocker in "
                   "the closure")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        model = shared_model(ctx, sources)
        graph = CallGraph(sources)
        depths = self._closure_depths(graph)
        entries: List[dict] = []
        findings: List[Finding] = []
        for src in sources:
            marks = _pure_marks(src)
            for qual, rec in model.functions.items():
                if rec.path != src.path:
                    continue
                in_closure = rec.name in depths
                budget = marks.get(rec.lineno)
                if not in_closure and budget is None:
                    continue
                blockers = _scan_blockers(rec.node, model, qual)
                if budget is not None:
                    findings.extend(self._contract_findings(
                        src, rec, blockers, budget))
                if in_closure and blockers.any(DEFAULT_ALLOC_BUDGET):
                    entries.append({
                        "function": qual,
                        "path": src.path,
                        "line": rec.lineno,
                        "depth": depths[rec.name],
                        "locks": [f"{tok}@{line}"
                                  for tok, line in blockers.locks],
                        "io": [f"{label}@{line}"
                               for label, line in blockers.io],
                        "allocs": blockers.allocs,
                        "severity": blockers.severity(),
                    })
        entries.sort(key=lambda e: (-e["severity"][0], -e["severity"][1],
                                    -e["severity"][2], e["depth"],
                                    e["function"]))
        ctx.reports[self.name] = {
            "roots": [r for r in HOT_ROOTS if r in depths],
            "closure_size": len(depths),
            "alloc_budget": DEFAULT_ALLOC_BUDGET,
            "blockers": entries,
        }
        yield from findings

    @staticmethod
    def _closure_depths(graph: CallGraph) -> Dict[str, int]:
        """bare function name -> min call depth from a hot root, over
        the package call graph (name-resolved, the usual
        over-approximation, minus edges through names too generic to
        mean a package call — see ``_GENERIC_NAMES``)."""
        depths: Dict[str, int] = {}
        frontier = [r for r in HOT_ROOTS if r in graph.calls_by_name]
        for name in frontier:
            depths[name] = 0
        while frontier:
            nxt: List[str] = []
            for name in frontier:
                for callee in sorted(graph.calls_by_name.get(name, ())):
                    if callee in graph.calls_by_name and \
                            callee not in depths and \
                            callee not in _GENERIC_NAMES:
                        depths[callee] = depths[name] + 1
                        nxt.append(callee)
            frontier = nxt
        return depths

    def _contract_findings(self, src: SourceFile, rec: "locksets.FunctionRec",
                           blockers: _Blockers,
                           budget: int) -> Iterator[Finding]:
        for token, line in blockers.locks:
            yield Finding(
                self.name, src.path, line,
                f"{rec.qualname}() is contracted `# hot-path: pure` but "
                f"acquires {token}; hoist the lock out of the hot path "
                f"or drop the contract")
        for label, line in blockers.io:
            yield Finding(
                self.name, src.path, line,
                f"{rec.qualname}() is contracted `# hot-path: pure` but "
                f"calls {label}; pure hot-path code does no I/O or "
                f"logging")
        if blockers.allocs > budget:
            yield Finding(
                self.name, src.path, rec.lineno,
                f"{rec.qualname}() is contracted `# hot-path: pure` with "
                f"an allocation budget of {budget} but contains "
                f"{blockers.allocs} allocation sites; vectorize or hoist "
                f"them, or raise the contract's `alloc=` budget")


def render_report(report: dict) -> str:
    """The ranked vectorization-blockers report ``--report`` prints."""
    lines = [
        f"hot-path report: roots {', '.join(report['roots']) or '(none)'}"
        f" — closure of {report['closure_size']} function(s), "
        f"{len(report['blockers'])} with blockers "
        f"(alloc budget {report['alloc_budget']}/call)"]
    for i, e in enumerate(report["blockers"], start=1):
        parts = []
        if e["locks"]:
            parts.append("locks: " + ", ".join(e["locks"]))
        if e["io"]:
            parts.append("io: " + ", ".join(e["io"]))
        if e["allocs"]:
            parts.append(f"allocs: {e['allocs']}")
        lines.append(f"{i:3d}. {e['function']} ({e['path']}:{e['line']}) "
                     f"depth {e['depth']} — {'; '.join(parts)}")
    if not report["blockers"]:
        lines.append("  (clean: the closure is vectorization-ready)")
    return "\n".join(lines)

"""monotonic-time: wall clocks don't age liveness state.

Heartbeat aging, backoff deadlines, TTLs, and lease expiry must use
``time.monotonic()`` (or ``time.perf_counter()`` for latencies): a
wall-clock step — NTP correction, manual reset, VM resume — would age
every node's heartbeat at once and mass-evict a healthy cluster, or
collapse every backoff in the system to zero.

Wall clocks are only legitimate when the timestamp crosses a process
boundary (the advertiser's heartbeat *stamp* is the protocol's wall-clock
half — the consumer side deliberately ages its own local observations
instead of comparing clocks) or is shown to humans. Those uses carry a
``# analysis: disable=monotonic-time`` suppression with a justification.

Scope: the control-plane tree. ``workload/`` (training/serving code) is
exempt — step timing there is cosmetic.
"""

from __future__ import annotations

import ast
from typing import Iterator

from kubegpu_tpu.analysis.engine import Context, Finding, dotted_name

# (dotted suffix, replacement hint)
_WALL_CLOCKS = (
    ("time.time", "time.monotonic()"),
    ("datetime.now", "time.monotonic()"),
    ("datetime.utcnow", "time.monotonic()"),
    ("datetime.today", "time.monotonic()"),
    ("date.today", "time.monotonic()"),
)

_EXEMPT_TOP_DIRS = frozenset({"workload"})


class MonotonicTime:
    name = "monotonic-time"
    description = ("liveness/lifecycle/backoff logic must use monotonic "
                   "clocks, not time.time()/datetime.now()")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        for src in sources:
            if src.relparts and src.relparts[0] in _EXEMPT_TOP_DIRS:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                dotted = dotted_name(node)
                if dotted is None:
                    continue
                for suffix, hint in _WALL_CLOCKS:
                    if dotted == suffix or dotted.endswith("." + suffix):
                        yield Finding(
                            self.name, src.path, node.lineno,
                            f"wall clock `{dotted}` in control-plane code; "
                            f"use {hint} — or suppress with a justification "
                            f"if this timestamp crosses a process boundary "
                            f"or is purely human-facing")
                        break

"""codec-pairing: every codec encoder has a decoder and a round trip.

The codecs ARE the wire protocol (``core/codec.py``): annotations
between the advertiser, the scheduler, and the CRI hook, and the binary
records the streaming transport frames carry. An encoder without a
decoder is a write nobody can read back — state that silently falls out
of the checkpoint/restore story (the API server is the only checkpoint)
or frames nobody can parse. Two naming conventions are enforced, each
both ways, and — when a tests directory is available — both halves of
every pair must appear in the codec round-trip tests (``test_codec*.py``):

* annotation codecs: ``<thing>_to_annotation`` / ``annotation_to_<thing>``
* binary wire codecs: ``encode_<record>`` / ``decode_<record>``
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Iterator

from kubegpu_tpu.analysis.engine import Context, Finding

# (encoder pattern, decoder pattern, decoder name template, encoder
# name template) per convention
_CONVENTIONS = (
    (re.compile(r"^(?P<stem>\w+)_to_annotation$"),
     re.compile(r"^annotation_to_(?P<stem>\w+)$"),
     "annotation_to_{stem}", "{stem}_to_annotation"),
    (re.compile(r"^encode_(?P<stem>\w+)$"),
     re.compile(r"^decode_(?P<stem>\w+)$"),
     "decode_{stem}", "encode_{stem}"),
)


class CodecPairing:
    name = "codec-pairing"
    description = ("every `<x>_to_annotation`/`encode_<x>` encoder needs "
                   "an `annotation_to_<x>`/`decode_<x>` decoder, and both "
                   "must appear in a round-trip test")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        for src in sources:
            if src.name != "codec.py":
                continue
            test_idents = _codec_test_identifiers(ctx)
            for enc_re, dec_re, dec_tpl, enc_tpl in _CONVENTIONS:
                encoders: dict = {}
                decoders: dict = {}
                for node in src.tree.body:
                    if not isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    m = enc_re.match(node.name)
                    if m:
                        encoders[m.group("stem")] = node
                    m = dec_re.match(node.name)
                    if m:
                        decoders[m.group("stem")] = node
                for stem in sorted(encoders):
                    node = encoders[stem]
                    if stem not in decoders:
                        yield Finding(
                            self.name, src.path, node.lineno,
                            f"encoder `{node.name}` has no decoder "
                            f"`{dec_tpl.format(stem=stem)}` — state that "
                            f"cannot be read back falls out of the wire/"
                            f"checkpoint story")
                for stem in sorted(decoders):
                    node = decoders[stem]
                    if stem not in encoders:
                        yield Finding(
                            self.name, src.path, node.lineno,
                            f"decoder `{node.name}` has no encoder "
                            f"`{enc_tpl.format(stem=stem)}` — nothing "
                            f"produces what this reads")
                if test_idents is None:
                    continue  # no tests tree in scope: pairing check only
                for stem in sorted(set(encoders) & set(decoders)):
                    for node in (encoders[stem], decoders[stem]):
                        if node.name not in test_idents:
                            yield Finding(
                                self.name, src.path, node.lineno,
                                f"`{node.name}` never appears in the codec "
                                f"round-trip tests (test_codec*.py) — an "
                                f"untested codec pair drifts")


def _codec_test_identifiers(ctx: Context) -> set | None:
    """Identifiers actually *referenced* (as names or attributes) in the
    codec round-trip tests. AST-level on purpose: a mention in a comment
    or docstring — or a longer name that merely contains the target as a
    substring — must not satisfy the tested-pair requirement."""
    if ctx.tests_dir is None or not os.path.isdir(ctx.tests_dir):
        return None
    idents: set = set()
    found = False
    for path in sorted(glob.glob(
            os.path.join(ctx.tests_dir, "test_codec*.py"))):
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        found = True
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                idents.add(node.id)
            elif isinstance(node, ast.Attribute):
                idents.add(node.attr)
    return idents if found else None

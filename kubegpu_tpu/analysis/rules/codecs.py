"""codec-pairing: every annotation encoder has a decoder and a round trip.

The annotation codec IS the wire protocol between the advertiser, the
scheduler, and the CRI hook (``core/codec.py``). An encoder without a
decoder is a write nobody can read back — state that silently falls out
of the checkpoint/restore story (the API server is the only checkpoint).
The repo's naming convention pairs ``<thing>_to_annotation`` with
``annotation_to_<thing>``; this rule enforces the pairing both ways and,
when a tests directory is available, requires both names to appear in the
codec round-trip tests (``test_codec*.py``).
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Iterator

from kubegpu_tpu.analysis.engine import Context, Finding

_ENCODE_RE = re.compile(r"^(?P<stem>\w+)_to_annotation$")
_DECODE_RE = re.compile(r"^annotation_to_(?P<stem>\w+)$")


class CodecPairing:
    name = "codec-pairing"
    description = ("every `<x>_to_annotation` encoder needs an "
                   "`annotation_to_<x>` decoder, and both must appear in a "
                   "round-trip test")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        for src in sources:
            if src.name != "codec.py":
                continue
            encoders: dict = {}
            decoders: dict = {}
            for node in src.tree.body:
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                m = _ENCODE_RE.match(node.name)
                if m:
                    encoders[m.group("stem")] = node
                m = _DECODE_RE.match(node.name)
                if m:
                    decoders[m.group("stem")] = node
            test_idents = _codec_test_identifiers(ctx)
            for stem in sorted(encoders):
                node = encoders[stem]
                if stem not in decoders:
                    yield Finding(
                        self.name, src.path, node.lineno,
                        f"encoder `{node.name}` has no decoder "
                        f"`annotation_to_{stem}` — annotation state that "
                        f"cannot be read back falls out of the API-server "
                        f"checkpoint")
            for stem in sorted(decoders):
                node = decoders[stem]
                if stem not in encoders:
                    yield Finding(
                        self.name, src.path, node.lineno,
                        f"decoder `{node.name}` has no encoder "
                        f"`{stem}_to_annotation` — nothing produces what "
                        f"this reads")
            if test_idents is None:
                continue  # no tests tree in scope: pairing check only
            for stem in sorted(set(encoders) & set(decoders)):
                for node in (encoders[stem], decoders[stem]):
                    if node.name not in test_idents:
                        yield Finding(
                            self.name, src.path, node.lineno,
                            f"`{node.name}` never appears in the codec "
                            f"round-trip tests (test_codec*.py) — an "
                            f"untested codec pair drifts")


def _codec_test_identifiers(ctx: Context) -> set | None:
    """Identifiers actually *referenced* (as names or attributes) in the
    codec round-trip tests. AST-level on purpose: a mention in a comment
    or docstring — or a longer name that merely contains the target as a
    substring — must not satisfy the tested-pair requirement."""
    if ctx.tests_dir is None or not os.path.isdir(ctx.tests_dir):
        return None
    idents: set = set()
    found = False
    for path in sorted(glob.glob(
            os.path.join(ctx.tests_dir, "test_codec*.py"))):
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        found = True
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                idents.add(node.id)
            elif isinstance(node, ast.Attribute):
                idents.add(node.attr)
    return idents if found else None

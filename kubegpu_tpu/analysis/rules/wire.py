"""Wire-contract: both ends of every wire must agree, statically.

PAPER.md's whole mechanism is a cross-process contract — annotations
and binary frames written by one component and decoded by another — and
since PR 9 that contract spans TWO negotiated framings over one route
table. A route, frame type, codec tag, or typed-error mapping added on
one side with no counterpart on the other is exactly the bug class no
unit test reliably catches (each side is self-consistent; only the
pairing is broken). Four paired surfaces are checked:

* **routes** — in any module defining a ``_route_request`` dual-wire
  route table, every client ``self._req(method, path)`` call must hit
  a served ``(method, first-segment)`` and every served route must
  have a client caller. Deliberately curl-only surfaces (``/healthz``,
  the ``/debug/*`` endpoints) carry justified suppressions the audit
  keeps honest.
* **frame types** — every member of a ``_FRAME_TYPES`` registry must
  be both *sent* (an argument to a ``send_frame``/``encode_frame``
  call) and *dispatched* (compared against somewhere): a type nobody
  sends is dead protocol surface, a type nobody dispatches poisons the
  peer's connection.
* **codec tags** — module-level ``_T_*`` wire tags must appear in both
  an ``encode*`` and a ``decode*`` function: a tag only the encoder
  knows produces frames the decoder rejects, and a decode-only tag is
  unreachable protocol.
* **typed-error maps** — within a route-table module, every dispatch
  site that maps typed errors to statuses (``except NotFound`` ->
  ``404``) must carry the SAME mapping set as every other dispatch
  site (the JSON handler and the stream handler are two wires over one
  contract), and the client must reconstruct exactly those pairs
  (``status == 404`` -> ``raise NotFound``). The front door's flow
  control rides this check too: ``TooManyRequests -> 429`` (REJECT
  frame on the stream wire) and ``QuotaExceeded -> 403`` must be
  exhaustive across both dispatch sites and client-reconstructed.
* **error-detail keys** — every key the server's ``_error_body()``
  writes into the typed-error payload must be READ somewhere on the
  client side of the module: a detail key the server sends that no
  client code consumes is a one-sided surface (exactly the
  retry-after bug class — the server advises ``retry_after_s``, the
  client's retry policy silently ignores it).
* **forward tables** — a hop module (one assigning
  ``FORWARDED_ROUTES``, i.e. the watch-cache proxy) re-serves the
  route-table module's whole client surface: every first segment a
  package client can reach must appear in ``LOCAL_ROUTES`` or
  ``FORWARDED_ROUTES`` (a segment in neither is a request the hop
  404s that the origin serves — a hole in the hop), and the hop's
  ``_forward()`` must re-raise exactly the typed-error pairs the
  origin's dispatch sites map — anything less degrades a typed error
  to a generic failure crossing the hop, anything more is dead hop
  surface.

Everything is matched by name and structure over the AST — no imports,
no execution — so the fixtures and the real tree are judged alike.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from kubegpu_tpu.analysis.engine import Context, Finding, SourceFile

ROUTE_TABLE_FN = "_route_request"
CLIENT_REQ = "_req"
ERROR_BODY_FN = "_error_body"
FORWARD_TABLES = ("LOCAL_ROUTES", "FORWARDED_ROUTES")
FORWARD_FN = "_forward"
FRAME_REGISTRY = "_FRAME_TYPES"
SEND_FNS = frozenset({"send_frame", "encode_frame", "send_raw"})
TAG_PREFIX = "_T_"
# broad classes never part of the typed-error contract
UNTYPED = frozenset({"Exception", "BaseException", "OSError"})


class WireContract:
    name = "wire-contract"
    description = ("client routes vs the _route_request table, "
                   "_FRAME_TYPES send vs dispatch, _T_* encode vs "
                   "decode tag sets, typed-error status maps across "
                   "both wires, and the proxy hop's forward tables vs "
                   "the client surface they must cover")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        for src in sources:
            route_fns = [node for node in ast.walk(src.tree)
                         if isinstance(node, ast.FunctionDef)
                         and node.name == ROUTE_TABLE_FN]
            if route_fns:
                yield from self._check_routes(src, route_fns)
                yield from self._check_error_maps(src)
                yield from self._check_error_detail(src)
            yield from self._check_codec_tags(src)
        yield from self._check_frame_types(sources)
        yield from self._check_forward_tables(sources)

    # ---- routes -------------------------------------------------------------

    def _check_routes(self, src: SourceFile,
                      route_fns: List[ast.FunctionDef]) -> Iterator[Finding]:
        served: Dict[str, Set[str]] = {}
        served_lines: Dict[str, int] = {}
        for fn in route_fns:
            _scan_route_table(fn, served, served_lines)
        client: Dict[Tuple[str, str], int] = {}
        for call, method, path in _client_requests(src.tree):
            seg = _first_segment(path)
            if seg is not None:
                client.setdefault((method, seg), call.lineno)
        for (method, seg), lineno in sorted(client.items(),
                                            key=lambda kv: kv[1]):
            methods = served.get(seg)
            if methods is None:
                yield Finding(
                    self.name, src.path, lineno,
                    f"client sends {method} /{seg} but the "
                    f"{ROUTE_TABLE_FN} table serves no /{seg} route — "
                    f"a request with no server counterpart")
            elif methods and method not in methods:
                yield Finding(
                    self.name, src.path, lineno,
                    f"client sends {method} /{seg} but the route table "
                    f"only serves {', '.join(sorted(methods))} for it")
        consumed = {seg for (_m, seg) in client}
        for seg in sorted(served):
            if seg not in consumed:
                yield Finding(
                    self.name, src.path, served_lines[seg],
                    f"route /{seg} is served but has no client caller "
                    f"in this module — a one-sided wire surface (add "
                    f"the client method, or waive a deliberately "
                    f"curl-only endpoint)")

    # ---- frame types --------------------------------------------------------

    def _check_frame_types(self, sources: list) -> Iterator[Finding]:
        registries: List[Tuple[SourceFile, int, List[str]]] = []
        sent: Set[str] = set()
        compared: Set[str] = set()
        for src in sources:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Assign) and \
                        any(isinstance(t, ast.Name)
                            and t.id == FRAME_REGISTRY
                            for t in node.targets):
                    value = node.value
                    if isinstance(value, ast.Call):
                        # frozenset({REQ, ...}): members live in the
                        # args, not the constructor's name
                        members = [m for arg in value.args
                                   for m in _name_refs(arg)]
                    else:
                        members = _name_refs(value)
                    if members:
                        registries.append((src, node.lineno, members))
                if isinstance(node, ast.Call):
                    fname = None
                    if isinstance(node.func, ast.Attribute):
                        fname = node.func.attr
                    elif isinstance(node.func, ast.Name):
                        fname = node.func.id
                    if fname in SEND_FNS:
                        for arg in node.args:
                            sent.update(_name_refs(arg))
                if isinstance(node, ast.Compare):
                    compared.update(_name_refs(node))
        for src, lineno, members in registries:
            for member in members:
                if member not in sent:
                    yield Finding(
                        self.name, src.path, lineno,
                        f"frame type {member} is registered in "
                        f"{FRAME_REGISTRY} but nothing ever sends it — "
                        f"dead protocol surface, or a sender is missing")
                if member not in compared:
                    yield Finding(
                        self.name, src.path, lineno,
                        f"frame type {member} is registered in "
                        f"{FRAME_REGISTRY} but no reader dispatches on "
                        f"it — a peer sending it poisons the connection")

    # ---- codec tags ---------------------------------------------------------

    def _check_codec_tags(self, src: SourceFile) -> Iterator[Finding]:
        tags: Dict[str, int] = {}
        for node in src.tree.body:
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name) and \
                            target.id.startswith(TAG_PREFIX) and \
                            isinstance(node.value, ast.Constant):
                        tags[target.id] = node.lineno
        if not tags:
            return
        encoded: Set[str] = set()
        decoded: Set[str] = set()
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            refs = {sub.id for sub in ast.walk(node)
                    if isinstance(sub, ast.Name) and
                    sub.id.startswith(TAG_PREFIX)}
            lowered = node.name.lower()
            if "encode" in lowered:
                encoded |= refs
            if "decode" in lowered:
                decoded |= refs
        for tag, lineno in sorted(tags.items(), key=lambda kv: kv[1]):
            if tag in encoded and tag not in decoded:
                yield Finding(
                    self.name, src.path, lineno,
                    f"wire tag {tag} is produced by an encoder but no "
                    f"decoder handles it — the peer rejects every frame "
                    f"that carries it")
            elif tag in decoded and tag not in encoded:
                yield Finding(
                    self.name, src.path, lineno,
                    f"wire tag {tag} is handled by a decoder but no "
                    f"encoder produces it — unreachable protocol "
                    f"surface (or the encoder half is missing)")

    # ---- typed-error maps ---------------------------------------------------

    def _check_error_maps(self, src: SourceFile) -> Iterator[Finding]:
        server_sites: List[Tuple[str, int, Set[Tuple[str, int]]]] = []
        client_sites: List[Tuple[str, int, Set[Tuple[str, int]]]] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            spairs = _server_error_pairs(node)
            if spairs:
                server_sites.append((node.name, node.lineno, spairs))
            cpairs = _client_error_pairs(node)
            if cpairs:
                client_sites.append((node.name, node.lineno, cpairs))
        if not server_sites:
            return
        union: Set[Tuple[str, int]] = set()
        for _name, _line, pairs in server_sites:
            union |= pairs
        for name, line, pairs in server_sites:
            for exc, status in sorted(union - pairs):
                yield Finding(
                    self.name, src.path, line,
                    f"typed-error mapping {exc} -> {status} is missing "
                    f"from dispatch site {name}() — present on another "
                    f"wire's dispatch, so one wire surfaces a typed "
                    f"error the other turns into a generic failure")
        client_union: Set[Tuple[str, int]] = set()
        for _name, _line, pairs in client_sites:
            client_union |= pairs
        if client_sites:
            for exc, status in sorted(union - client_union):
                yield Finding(
                    self.name, src.path, server_sites[0][1],
                    f"server maps {exc} -> {status} but no client site "
                    f"reconstructs {exc} from status {status} — the "
                    f"typed error degrades to a generic one on the wire")
            for exc, status in sorted(client_union - union):
                yield Finding(
                    self.name, src.path, client_sites[0][1],
                    f"client reconstructs {exc} from status {status} "
                    f"but no dispatch site ever maps it — dead client "
                    f"surface or a missing server mapping")

    # ---- forward tables (the proxy hop) -------------------------------------

    def _check_forward_tables(self, sources: list) -> Iterator[Finding]:
        """Cross-source, like frame types: the client surface and the
        canonical typed-error union come from the route-table modules
        (the ones defining ``_route_request`` — its importers serve the
        SAME table, so they add nothing); each hop module is then held
        to both. kubeclient-style ``_req`` callers speaking a foreign
        wire don't define a route table, so they never leak into the
        surface the hop must cover."""
        client_segs: Dict[str, str] = {}  # first segment -> method
        canonical: Set[Tuple[str, int]] = set()
        saw_origin = False
        for src in sources:
            if not any(isinstance(node, ast.FunctionDef)
                       and node.name == ROUTE_TABLE_FN
                       for node in ast.walk(src.tree)):
                continue
            saw_origin = True
            for _call, method, path in _client_requests(src.tree):
                seg = _first_segment(path)
                if seg is not None:
                    client_segs.setdefault(seg, method)
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    canonical |= _server_error_pairs(node)
        if not saw_origin:
            return  # no origin in view: nothing to hold a hop against
        for src in sources:
            tables: Dict[str, Set[str]] = {}
            table_line = 0
            for node in src.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name) and \
                            target.id in FORWARD_TABLES:
                        tables[target.id] = _string_members(node.value)
                        if target.id == FORWARD_TABLES[1]:
                            table_line = node.lineno
            if FORWARD_TABLES[1] not in tables:
                continue
            covered: Set[str] = set().union(*tables.values())
            for seg in sorted(set(client_segs) - covered):
                yield Finding(
                    self.name, src.path, table_line,
                    f"client sends {client_segs[seg]} /{seg} but the "
                    f"hop routes it neither locally (LOCAL_ROUTES) nor "
                    f"upstream (FORWARDED_ROUTES) — a hole in the hop: "
                    f"the proxy 404s a request the origin serves")
            hop_pairs: Set[Tuple[str, int]] = set()
            hop_line = table_line
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node.name == FORWARD_FN:
                    hop_pairs |= _client_error_pairs(node)
                    hop_line = node.lineno
            for exc, status in sorted(canonical - hop_pairs):
                yield Finding(
                    self.name, src.path, hop_line,
                    f"origin dispatch maps {exc} -> {status} but the "
                    f"hop's {FORWARD_FN}() never re-raises {exc} from "
                    f"{status} — the typed error degrades to a generic "
                    f"failure crossing the hop")
            for exc, status in sorted(hop_pairs - canonical):
                yield Finding(
                    self.name, src.path, hop_line,
                    f"{FORWARD_FN}() re-raises {exc} from status "
                    f"{status} but no origin dispatch site maps it — "
                    f"dead hop surface, or a missing origin mapping")

    # ---- error-detail keys --------------------------------------------------

    def _check_error_detail(self, src: SourceFile) -> Iterator[Finding]:
        """Every key ``_error_body()`` writes into the typed-error
        payload must be read somewhere OUTSIDE it in the same module
        (``doc.get("key")`` / ``doc["key"]``) — a detail key only the
        server knows is advice the client silently drops."""
        body_fns = [node for node in ast.walk(src.tree)
                    if isinstance(node, ast.FunctionDef)
                    and node.name == ERROR_BODY_FN]
        if not body_fns:
            return
        written: Dict[str, int] = {}
        inside: Set[int] = set()
        for fn in body_fns:
            for node in ast.walk(fn):
                inside.add(id(node))
                if isinstance(node, ast.Dict):
                    for key in node.keys:
                        if isinstance(key, ast.Constant) and \
                                isinstance(key.value, str):
                            written.setdefault(key.value, node.lineno)
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        if isinstance(target, ast.Subscript) and \
                                isinstance(target.slice, ast.Constant) \
                                and isinstance(target.slice.value, str):
                            written.setdefault(target.slice.value,
                                               target.lineno)
        read: Set[str] = set()
        for node in ast.walk(src.tree):
            if id(node) in inside:
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) and \
                        isinstance(arg0.value, str):
                    read.add(arg0.value)
            if isinstance(node, ast.Subscript) and \
                    not isinstance(node.ctx, ast.Store) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                read.add(node.slice.value)
        for key, lineno in sorted(written.items(), key=lambda kv: kv[1]):
            if key not in read:
                yield Finding(
                    self.name, src.path, lineno,
                    f"error-detail key {key!r} is written by "
                    f"{ERROR_BODY_FN}() but nothing in this module "
                    f"reads it back — server-sent advice the client "
                    f"silently drops (the retry-after bug class)")


# ---- helpers ----------------------------------------------------------------


def _name_refs(node: ast.AST) -> List[str]:
    """Plain or attribute name references under ``node``, by last
    component (``stream.PUSH`` -> ``PUSH``), constants excluded."""
    out: List[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
    return out


def _string_members(value: ast.AST) -> Set[str]:
    """String constants in a route-table literal: the members of
    ``frozenset({"pods", ...})`` (a Call wrapping a Set) or a bare
    set/tuple/list literal."""
    return {sub.value for sub in ast.walk(value)
            if isinstance(sub, ast.Constant)
            and isinstance(sub.value, str)}


def _client_requests(tree: ast.AST) \
        -> Iterator[Tuple[ast.Call, str, str]]:
    """Every ``*._req(<method literal>, <path>)`` call, with the path
    resolved through simple local bindings (``path = f"/watch?..."``
    then ``self._req("GET", path)``)."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        env: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                head = _literal_head(node.value, env)
                if head is not None:
                    env.setdefault(node.targets[0].id, head)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_req = (isinstance(func, ast.Attribute)
                      and func.attr == CLIENT_REQ) or \
                     (isinstance(func, ast.Name) and func.id == CLIENT_REQ)
            if not is_req or len(node.args) < 2:
                continue
            method = node.args[0]
            if not (isinstance(method, ast.Constant)
                    and isinstance(method.value, str)):
                continue
            path = _literal_head(node.args[1], env)
            if path is not None:
                yield node, method.value, path


def _literal_head(node: ast.AST, env: Dict[str, str]) -> Optional[str]:
    """The leading literal text of a string expression: a constant, an
    f-string's leading constant parts, the left side of ``+`` chains,
    or a name previously bound to one."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        head = ""
        for part in node.values:
            if isinstance(part, ast.Constant) and \
                    isinstance(part.value, str):
                head += part.value
            else:
                break
        return head or None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _literal_head(node.left, env)
    if isinstance(node, ast.Name):
        return env.get(node.id)
    return None


def _first_segment(path: str) -> Optional[str]:
    path = path.split("?")[0]
    parts = [p for p in path.split("/") if p]
    return parts[0] if parts else None


def _scan_route_table(fn: ast.FunctionDef, served: Dict[str, Set[str]],
                      lines: Dict[str, int]) -> None:
    """Walk a route table function collecting ``(first segment ->
    methods)``. Branch structure carries the segment context downward:
    ``if parts[0] == "nodes":`` establishes the segment for the nested
    ``if method == "GET":`` checks. A loop over ``(("pvcs", ...),
    ("pvs", ...))`` binds its target names to those constants."""
    env: Dict[str, Set[str]] = {}

    def scan(stmts: List[ast.stmt], seg_ctx: Optional[Set[str]]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                _bind_loop_env(stmt, env)
                scan(list(stmt.body), seg_ctx)
                continue
            if isinstance(stmt, ast.If):
                segs = _segments_in_test(stmt.test, env)
                methods = _methods_in_test(stmt.test)
                ctx = segs or seg_ctx
                if ctx:
                    for seg in ctx:
                        entry = served.setdefault(seg, set())
                        entry.update(methods)
                        lines.setdefault(seg, stmt.lineno)
                scan(list(stmt.body), ctx)
                scan(list(stmt.orelse), seg_ctx)
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    scan([child], seg_ctx)

    scan(list(fn.body), None)


def _bind_loop_env(stmt: "ast.For | ast.AsyncFor",
                   env: Dict[str, Set[str]]) -> None:
    """``for kind, ... in (("pvcs", ...), ("pvs", ...)):`` binds
    ``kind`` to ``{"pvcs", "pvs"}`` for segment resolution."""
    if not isinstance(stmt.iter, (ast.Tuple, ast.List)):
        return
    targets: List[Optional[str]] = []
    if isinstance(stmt.target, ast.Name):
        targets = [stmt.target.id]
    elif isinstance(stmt.target, ast.Tuple):
        targets = [t.id if isinstance(t, ast.Name) else None
                   for t in stmt.target.elts]
    for row in stmt.iter.elts:
        values: List[ast.expr] = [row]
        if isinstance(row, (ast.Tuple, ast.List)):
            values = list(row.elts)
        for i, name in enumerate(targets):
            if name is None or i >= len(values):
                continue
            val = values[i]
            if isinstance(val, ast.Constant) and isinstance(val.value, str):
                env.setdefault(name, set()).add(val.value)


def _segments_in_test(test: ast.AST,
                      env: Dict[str, Set[str]]) -> Set[str]:
    """First-segment constants this test pins ``parts`` to:
    ``parts == ["watch"]``, ``parts[0] == "nodes"``,
    ``parts[:2] == ["debug", "pod"]``, ``parts[0] == kind`` (via the
    loop env)."""
    segs: Set[str] = set()
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1 or \
                not isinstance(node.ops[0], (ast.Eq,)):
            continue
        left, right = node.left, node.comparators[0]
        if not _is_parts_expr(left):
            left, right = right, left
            if not _is_parts_expr(left):
                continue
        first = _first_of_comparand(right, env)
        segs.update(first)
    return segs


def _is_parts_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Name) and node.id == "parts":
        return True
    if isinstance(node, ast.Subscript) and \
            isinstance(node.value, ast.Name) and node.value.id == "parts":
        sl = node.slice
        if isinstance(sl, ast.Constant) and sl.value == 0:
            return True
        # parts[:2] pins a PREFIX (element 0 is the segment);
        # parts[2:] compares a tail and says nothing about it
        if isinstance(sl, ast.Slice) and sl.lower is None:
            return True
    return False


def _first_of_comparand(node: ast.AST,
                        env: Dict[str, Set[str]]) -> Set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.List, ast.Tuple)) and node.elts:
        return _first_of_comparand(node.elts[0], env)
    if isinstance(node, ast.Name):
        return set(env.get(node.id, set()))
    return set()


def _methods_in_test(test: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1 or \
                not isinstance(node.ops[0], ast.Eq):
            continue
        left, right = node.left, node.comparators[0]
        if isinstance(right, ast.Name) and right.id == "method":
            left, right = right, left
        if isinstance(left, ast.Name) and left.id == "method" and \
                isinstance(right, ast.Constant) and \
                isinstance(right.value, str):
            out.add(right.value)
    return out


def _server_error_pairs(fn: ast.AST) -> Set[Tuple[str, int]]:
    """``except NotFound: ... 404 ...`` pairs in one dispatch site."""
    pairs: Set[Tuple[str, int]] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not isinstance(handler.type, (ast.Name, ast.Attribute)):
                continue  # tuples and bare excepts are not typed maps
            exc = handler.type.id if isinstance(handler.type, ast.Name) \
                else handler.type.attr
            if exc in UNTYPED:
                continue
            statuses = {sub.value for stmt in handler.body
                        for sub in ast.walk(stmt)
                        if isinstance(sub, ast.Constant)
                        and isinstance(sub.value, int)
                        and 400 <= sub.value <= 599}
            for status in statuses:
                pairs.add((exc, status))
    return pairs


def _client_error_pairs(fn: ast.AST) -> Set[Tuple[str, int]]:
    """``if status == 404: raise (self._server_error()NotFound(...)``
    pairs in one client site."""
    pairs: Set[Tuple[str, int]] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        status = _status_compared(node.test)
        if status is None:
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Raise) or sub.exc is None:
                    continue
                exc = _raised_error_name(sub.exc)
                if exc is not None and exc not in UNTYPED:
                    pairs.add((exc, status))
    return pairs


def _status_compared(test: ast.AST) -> Optional[int]:
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1 or \
                not isinstance(node.ops[0], ast.Eq):
            continue
        left, right = node.left, node.comparators[0]
        if isinstance(left, ast.Constant):
            left, right = right, left
        name = left.id if isinstance(left, ast.Name) \
            else left.attr if isinstance(left, ast.Attribute) else None
        if name in ("status", "code") and isinstance(right, ast.Constant) \
                and isinstance(right.value, int) and \
                400 <= right.value <= 599:
            return int(right.value)
    return None


def _raised_error_name(exc: ast.AST) -> Optional[str]:
    """The typed-error class a raise reconstructs: ``raise NotFound(x)``
    or ``raise self._server_error(NotFound, doc)`` (first capitalized
    Name wins)."""
    if isinstance(exc, ast.Call):
        func = exc.func
        if isinstance(func, ast.Name) and func.id[:1].isupper():
            return func.id
        for arg in exc.args:
            if isinstance(arg, ast.Name) and arg.id[:1].isupper():
                return arg.id
        if isinstance(func, ast.Attribute):
            for arg in exc.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and \
                            sub.id[:1].isupper():
                        return sub.id
    if isinstance(exc, ast.Name) and exc.id[:1].isupper():
        return exc.id
    return None

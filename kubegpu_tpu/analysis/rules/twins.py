"""Dual-path drift rules: the vector/scalar twin contract.

PR 14 made every scheduler hot path a *dual implementation*: a masked
numpy chain shadowing a scalar predicate chain
(``scheduler/vectorized.py`` vs the object path in
``scheduler/core.py``), a convolution-table mesh search shadowing the
preserved reference enumeration (``topology/mesh.py``), and a columnar
fleet mirror shadowing the object cache (``scheduler/cache.py``). The
only thing holding the twins together is hand-written differential
tests — so the twin relationships themselves become checked contracts:

* ``twin-coverage`` — every vectorized kernel declares its scalar
  original with a ``# twin-of: <qualname>`` comment bound to its
  ``def``. The declaration must *resolve* (the named original exists in
  the scanned tree), the pair must be *exercised* (one of the two names
  appears, AST-identifier-checked like codec-pairing's tested-in rule,
  in the differential tests ``test_vector*.py``), and — the coverage
  half — every scalar DEFAULT-chain predicate must either be the
  declared original of some twin or carry a ``# vector-gate:``
  declaration naming how the masked pass routes its pods/nodes to the
  scalar chain. An undeclared default predicate is a predicate the
  masked pass may silently disagree with.

* ``mirror-maintenance`` — dataflow over the scheduler cache (built on
  the PR 10 CFG engine): in a class that owns a fleet-columns mirror
  (``self.columns``), every path that bumps a fit generation
  (``_invalidate_locked`` / ``_invalidate_all_locked`` call sites) must
  first update the mirror (a ``self.columns.<...>()`` call, or the
  None-guarded ``if self.columns is not None:`` form, credited at the
  guard) — on ALL paths, exception edges included. The invalidate
  methods themselves must propagate the new generation into the columns
  (``set_gen`` / ``bump_all_gens``), and nothing outside them may write
  the generation map directly.

* ``reason-parity`` — failure-reason string literals emitted by the
  vector chain (``_REASON*`` constants and list-display literals inside
  twin-declared functions) must be drawn from the exact literal set the
  scalar chain emits (every literal in ``predicates.py``/``factory.py``)
  — a drifted ``Insufficient ...`` string is a verdict the differential
  tests would report as a reason mismatch in production, caught here at
  parse time.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from kubegpu_tpu.analysis.dataflow import (EXCEPT, ControlFlowGraph,
                                           build_cfg, call_names)
from kubegpu_tpu.analysis.engine import (Context, Finding, SourceFile,
                                         bound_comments, dotted_name,
                                         walk_functions)

TWIN_RE = re.compile(r"#\s*twin-of:\s*(?P<qual>[A-Za-z_][\w.]*)")
GATE_RE = re.compile(r"#\s*vector-gate:\s*(?P<why>\S.*)")

#: Differential-test file pattern the exercised check scans (the
#: vector-vs-scalar proof suite).
DIFF_TEST_GLOB = "test_vector*.py"


# ---- shared helpers ---------------------------------------------------------


_functions = walk_functions


def _bound_comments(
        src: SourceFile,
        regex: "re.Pattern[str]") -> List[Tuple[int, Optional[int], str]]:
    """The shared :func:`engine.bound_comments` walk, with the match's
    first capture group extracted (the qualname / justification)."""
    return [(cline, dline, m.group(1))
            for cline, dline, m in bound_comments(src, regex)]


def _diff_test_identifiers(ctx: Context) -> Optional[Set[str]]:
    """Identifiers referenced (names or attributes) in the differential
    tests — AST-level like codec-pairing's tested-in check, so a
    docstring mention cannot satisfy the exercised requirement. None
    when no tests tree (or no differential test file) is in scope."""
    if ctx.tests_dir is None or not os.path.isdir(ctx.tests_dir):
        return None
    idents: Set[str] = set()
    found = False
    for path in sorted(glob.glob(
            os.path.join(ctx.tests_dir, DIFF_TEST_GLOB))):
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        found = True
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                idents.add(node.id)
            elif isinstance(node, ast.Attribute):
                idents.add(node.attr)
    return idents if found else None


# ---- twin-coverage ----------------------------------------------------------


class _TwinDecl:
    __slots__ = ("src", "comment_line", "fn_name", "fn_qual", "target")

    def __init__(self, src: SourceFile, comment_line: int, fn_name: str,
                 fn_qual: str, target: str) -> None:
        self.src = src
        self.comment_line = comment_line
        self.fn_name = fn_name
        self.fn_qual = fn_qual
        self.target = target


class TwinCoverage:
    name = "twin-coverage"
    description = ("vectorized kernels declare their scalar originals "
                   "with `# twin-of:` (resolving, and exercised by the "
                   "differential tests); every DEFAULT-chain predicate "
                   "needs a declared twin or a `# vector-gate:`")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        defined: Set[str] = set()          # bare terminal names
        defined_quals: Set[str] = set()    # Class.method qualnames
        class_names: Set[str] = set()
        module_stems: Set[str] = set()
        toplevel_by_module: Dict[str, Set[str]] = {}
        fn_by_line: Dict[str, Dict[int, Tuple[str, Any]]] = {}
        for src in sources:
            stem = src.name[:-3] if src.name.endswith(".py") else src.name
            module_stems.add(stem)
            toplevel = toplevel_by_module.setdefault(stem, set())
            per_line: Dict[int, Tuple[str, Any]] = {}
            for qual, node in _functions(src.tree):
                defined.add(qual.rsplit(".", 1)[-1])
                defined_quals.add(qual)
                if "." not in qual:
                    toplevel.add(qual)
                per_line[node.lineno] = (qual, node)
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    class_names.add(node.name)
            fn_by_line[src.path] = per_line

        decls: List[_TwinDecl] = []
        for src in sources:
            per_line = fn_by_line[src.path]
            for cline, dline, qual in _bound_comments(src, TWIN_RE):
                bound = per_line.get(dline) if dline is not None else None
                if bound is None:
                    yield Finding(
                        self.name, src.path, cline,
                        f"`# twin-of: {qual}` binds to no function "
                        f"definition — move it onto (or directly above) "
                        f"the twin's `def`; an orphaned declaration "
                        f"looks like coverage and provides none")
                    continue
                fn_qual, node = bound
                decls.append(_TwinDecl(src, cline,
                                       fn_qual.rsplit(".", 1)[-1],
                                       fn_qual, qual))

        test_idents = _diff_test_identifiers(ctx)
        for decl in decls:
            parts = decl.target.split(".")
            terminal = parts[-1]
            if not self._resolves(parts, defined, defined_quals,
                                  class_names, module_stems,
                                  toplevel_by_module):
                yield Finding(
                    self.name, decl.src.path, decl.comment_line,
                    f"`# twin-of: {decl.target}` does not resolve in "
                    f"the scanned tree — the twin binding is dangling "
                    f"(renamed, moved, or removed original?)")
                continue
            if test_idents is not None and \
                    decl.fn_name not in test_idents and \
                    terminal not in test_idents:
                yield Finding(
                    self.name, decl.src.path, decl.comment_line,
                    f"twin pair `{decl.fn_qual}` <-> `{terminal}` never "
                    f"appears in the differential tests "
                    f"({DIFF_TEST_GLOB}) — an unexercised twin pair "
                    f"drifts unobserved")

        targets = {d.target.rsplit(".", 1)[-1] for d in decls}
        for src in sources:
            yield from self._check_default_chain(src, targets)

    @staticmethod
    def _resolves(parts: List[str], defined: Set[str],
                  defined_quals: Set[str], class_names: Set[str],
                  module_stems: Set[str],
                  toplevel_by_module: Dict[str, Set[str]]) -> bool:
        """A qualified target must resolve through its last TWO
        segments — ``Class.method`` against a scanned class, or
        ``module.function`` against that module's top level — so a
        moved or mis-pathed original cannot hide behind a same-named
        function elsewhere in the tree. A bare single-segment target
        falls back to the permissive any-function match."""
        terminal = parts[-1]
        if len(parts) == 1:
            return terminal in defined
        parent = parts[-2]
        if parent in class_names:
            return f"{parent}.{terminal}" in defined_quals
        if parent in module_stems:
            return terminal in toplevel_by_module.get(parent, set())
        return False

    def _check_default_chain(self, src: SourceFile,
                             twin_targets: Set[str]) -> Iterator[Finding]:
        """The coverage half: DEFAULT_PREDICATE_NAMES x FIT_PREDICATES
        (wherever both shapes appear — the factory, or a fixture
        modeling it) must be fully twin-covered or vector-gated."""
        default_names = self._default_names(src.tree)
        registry = self._fit_registry(src.tree)
        if default_names is None or registry is None:
            return
        builder_defs: Dict[str, Any] = {
            qual.rsplit(".", 1)[-1]: node
            for qual, node in _functions(src.tree)}
        gated: Set[str] = set()
        for _cline, dline, _why in _bound_comments(src, GATE_RE):
            for bname, node in builder_defs.items():
                if getattr(node, "lineno", None) == dline:
                    gated.add(bname)
        seen: Set[str] = set()
        for pred_name in default_names:
            entry = registry.get(pred_name)
            if entry is None:
                continue
            builder, line = entry
            if builder in seen:
                continue
            seen.add(builder)
            if builder in twin_targets or builder in gated:
                continue
            node = builder_defs.get(builder)
            if node is not None and call_names(node) & twin_targets:
                continue  # one hop: the builder wraps a declared original
            yield Finding(
                self.name, src.path,
                getattr(node, "lineno", line),
                f"default predicate `{pred_name}` (builder `{builder}`) "
                f"has no declared vector twin and no `# vector-gate:` "
                f"declaration — the masked pass's behavior for it is an "
                f"unchecked assumption")

    @staticmethod
    def _default_names(tree: ast.AST) -> Optional[List[str]]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == "DEFAULT_PREDICATE_NAMES" and \
                    isinstance(node.value, (ast.Tuple, ast.List)):
                out = [e.value for e in node.value.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str)]
                return out
        return None

    @staticmethod
    def _fit_registry(tree: ast.AST) -> \
            Optional[Dict[str, Tuple[str, int]]]:
        """FIT_PREDICATES entries -> (builder function name, line).
        Handles the repo's shapes: ``_declare(...)(_p_host)``,
        ``_declare(...)(_p_max_volumes("kind", 39))``, and a bare
        builder name."""
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "FIT_PREDICATES"
                    and isinstance(node.value, ast.Dict)):
                continue
            out: Dict[str, Tuple[str, int]] = {}
            for key, value in zip(node.value.keys, node.value.values):
                if not (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    continue
                builder = _builder_name(value)
                if builder is not None:
                    out[key.value] = (builder, value.lineno)
            return out
        return None


def _builder_name(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Call):
        # `_declare(...)(builder)` — the builder is the outer call's arg
        if value.args:
            inner = value.args[0]
            if isinstance(inner, ast.Name):
                return inner.id
            if isinstance(inner, ast.Call):
                got = dotted_name(inner.func)
                if got is not None:
                    return got.rsplit(".", 1)[-1]
        got = dotted_name(value.func)
        if got is not None:
            return got.rsplit(".", 1)[-1]
    return None


# ---- mirror-maintenance -----------------------------------------------------

_INVALIDATE_NAMES = ("self._invalidate_locked", "self._invalidate_all_locked")
_GEN_PROPAGATORS = frozenset({"set_gen", "bump_all_gens"})


class MirrorMaintenance:
    name = "mirror-maintenance"
    description = ("every generation bump in a fleet-columns-owning "
                   "cache must be preceded by a columns update on all "
                   "paths (exception edges included); the invalidators "
                   "must propagate generations into the mirror")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        for src in sources:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef) and \
                        self._owns_columns(node):
                    yield from self._check_class(src, node)

    @staticmethod
    def _owns_columns(cls: ast.ClassDef) -> bool:
        for node in ast.walk(cls):
            if isinstance(node, ast.Attribute) and \
                    dotted_name(node) == "self.columns":
                return True
        return False

    def _check_class(self, src: SourceFile,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in ("_invalidate_locked", "_invalidate_all_locked"):
                if not self._propagates_gen(item):
                    yield Finding(
                        self.name, src.path, item.lineno,
                        f"{cls.name}.{item.name}() bumps generations but "
                        f"never mirrors them into the fleet columns "
                        f"(self.columns.set_gen / bump_all_gens) — the "
                        f"mask memo would serve verdicts the bump meant "
                        f"to retire")
                continue
            yield from self._check_method(src, cls, item)

    @staticmethod
    def _propagates_gen(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                got = dotted_name(node.func)
                if got is not None and got.startswith("self.columns.") and \
                        got.rsplit(".", 1)[-1] in _GEN_PROPAGATORS:
                    return True
        return False

    def _check_method(self, src: SourceFile, cls: ast.ClassDef,
                      fn: ast.AST) -> Iterator[Finding]:
        bumps = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
                 and dotted_name(n.func) in _INVALIDATE_NAMES]
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Subscript) and \
                            dotted_name(tgt.value) == "self._gen":
                        yield Finding(
                            self.name, src.path, node.lineno,
                            f"{cls.name}.{fn.name}() writes the "
                            f"generation map directly; bump through "
                            f"_invalidate_locked so the columns mirror "
                            f"moves in lockstep")
        if not bumps:
            return
        cfg = build_cfg(fn)
        dirty = self._dirty_tags(cfg)
        reported: Set[int] = set()
        for node in cfg.nodes:
            if node.kind != "stmt":
                continue
            if not any(isinstance(sub, ast.Call)
                       and dotted_name(sub.func) in _INVALIDATE_NAMES
                       for a in node.effect_asts()
                       for sub in ast.walk(a)):
                continue
            tags = dirty.get(node.idx, set())
            if not tags:
                continue
            line = getattr(node.stmt, "lineno", fn.lineno)
            if line in reported:
                continue
            reported.add(line)
            handlers = sorted(t.lineno for t in tags if t is not None)
            via = []
            if None in tags:
                via.append("a normal path")
            if handlers:
                via.append("an exception edge (handler at line "
                           + ", ".join(str(h) for h in handlers) + ")")
            yield Finding(
                self.name, src.path, line,
                f"{cls.name}.{fn.name}() bumps a fit generation with no "
                f"fleet-columns update on {' and '.join(via)} — the "
                f"mirror and the objects it mirrors diverge")

    def _dirty_tags(self, cfg: ControlFlowGraph) -> Dict[int, set]:
        """Forward tag propagation from entry: a node's in-set holds
        ``None`` when some normal path reaches it with the mirror not
        yet updated, or an ``excepthandler`` when an exception edge
        does. A maintaining statement clears the state (the mirror is
        in sync past it)."""
        in_tags: Dict[int, set] = {}
        out_tags: Dict[int, set] = {cfg.entry.idx: {None}}
        work = [cfg.entry.idx]
        while work:
            idx = work.pop()
            node_in = in_tags.get(idx, set())
            node_out = out_tags.get(idx, set())
            for edge in cfg.succs[idx]:
                payload = node_in | node_out if edge.kind == EXCEPT \
                    else node_out
                if not payload:
                    continue
                dst_in = in_tags.setdefault(edge.dst, set())
                if payload <= dst_in:
                    continue
                dst_in |= payload
                dst = cfg.nodes[edge.dst]
                if dst.kind == "handler":
                    new_out = {dst.handler} if dst_in else set()
                elif self._maintains(dst):
                    new_out = set()
                else:
                    new_out = set(dst_in)
                out_tags[edge.dst] = new_out
                work.append(edge.dst)
        return in_tags

    @staticmethod
    def _maintains(node: object) -> bool:
        stmt = getattr(node, "stmt", None)
        if getattr(node, "kind", None) != "stmt":
            return False
        if isinstance(stmt, ast.If):
            # the None-guarded form: `if self.columns is not None:
            #     self.columns.charge(...)` — credited at the guard so
            # the numpy-less branch is not a false positive
            test_reads = any(
                isinstance(sub, ast.Attribute)
                and dotted_name(sub) == "self.columns"
                for sub in ast.walk(stmt.test))
            body_updates = any(
                isinstance(sub, ast.Call)
                and (dotted_name(sub.func) or "").startswith("self.columns.")
                for s in stmt.body for sub in ast.walk(s))
            return test_reads and body_updates
        for a in getattr(node, "effect_asts", lambda: [])():
            for sub in ast.walk(a):
                if isinstance(sub, ast.Call):
                    got = dotted_name(sub.func)
                    if got is not None and got.startswith("self.columns."):
                        return True
        return False


# ---- reason-parity ----------------------------------------------------------

_REASON_NAME_RE = re.compile(r"^_REASON")
#: Modules whose string literals define the scalar chain's reason
#: vocabulary (the allowed set; over-approximate — errs silent).
_SCALAR_REASON_FILES = ("predicates.py", "factory.py")


def _norm_str(node: ast.AST) -> Optional[str]:
    """A string constant, or an f-string with every interpolation
    normalized to ``{}`` — so ``f"Insufficient {res}"`` in the vector
    chain matches the scalar chain's identical template."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for part in node.values:
            if isinstance(part, ast.Constant) and \
                    isinstance(part.value, str):
                parts.append(part.value)
            else:
                parts.append("{}")
        return "".join(parts)
    return None


class ReasonParity:
    name = "reason-parity"
    description = ("failure-reason literals in the vector chain "
                   "(`_REASON*` constants, list literals in twin-"
                   "declared functions) must match the scalar chain's "
                   "literal set verbatim — no drifted reason strings")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        pool: Set[str] = set()
        for src in sources:
            if src.name in _SCALAR_REASON_FILES:
                for node in ast.walk(src.tree):
                    got = _norm_str(node)
                    if got is not None:
                        pool.add(got)
        if not pool:
            return  # no scalar chain in scope: nothing to compare against
        for src in sources:
            if src.name in _SCALAR_REASON_FILES:
                continue
            yield from self._check_source(src, pool)

    def _check_source(self, src: SourceFile,
                      pool: Set[str]) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    _REASON_NAME_RE.match(node.targets[0].id):
                got = _norm_str(node.value)
                if got is not None and got not in pool:
                    yield Finding(
                        self.name, src.path, node.lineno,
                        f"reason constant {node.targets[0].id} = "
                        f"{got!r} is not a literal the scalar chain "
                        f"emits ({'/'.join(_SCALAR_REASON_FILES)}) — "
                        f"twin reason drift")
        twin_defs = {dline for _c, dline, _q in _bound_comments(src, TWIN_RE)}
        if not twin_defs:
            return
        for qual, fn in _functions(src.tree):
            if fn.lineno not in twin_defs:  # type: ignore[attr-defined]
                continue
            for node in ast.walk(fn):
                elts: List[ast.AST] = []
                if isinstance(node, ast.List):
                    elts = list(node.elts)
                elif isinstance(node, ast.ListComp):
                    elts = [node.elt]
                for elt in elts:
                    got = _norm_str(elt)
                    if got is not None and got not in pool:
                        yield Finding(
                            self.name, src.path, elt.lineno,
                            f"reason literal {got!r} emitted by twin "
                            f"`{qual}` is not in the scalar chain's "
                            f"literal set — the differential contract "
                            f"requires verbatim reason strings")

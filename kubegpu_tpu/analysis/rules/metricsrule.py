"""metric-registration: one metrics registry, consistent names.

Every metric the system emits is declared exactly once, at module level,
in ``metrics.py`` — so dashboards have one place to discover names and a
renamed metric cannot half-exist. Names are snake_case; counters carry
the ``_total`` suffix and histograms a unit suffix, Prometheus-style.
Modules emit through the declared module-level objects
(``metrics.EVICTIONS.inc()``); referencing an undeclared ``metrics.X``
is a typo that would otherwise surface as an AttributeError mid-flight.

Completeness is checked too: a metric declared in the registry must be
covered by ``reset_all()`` (or its value leaks across test/bench runs)
and by the Prometheus exposition (``prometheus_text``, wherever it
lives) — a function that iterates ``all_metrics()`` is exhaustive by
construction; one that hand-enumerates must name every declared metric.
This closes the drift class where a new metric silently never exports.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from kubegpu_tpu.analysis.engine import (Context, Finding, SourceFile,
                                         dotted_name)

_METRIC_TYPES = frozenset({"Counter", "Gauge", "Histogram",
                           "LabeledHistogram", "LabeledCounter",
                           "LabeledGauge"})
_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HISTOGRAM_UNITS = ("_microseconds", "_milliseconds", "_seconds", "_us",
                    "_ms", "_bytes", "_total")


def _metric_ctor(node: ast.AST) -> str | None:
    """'Counter'/'Gauge'/'Histogram' when ``node`` constructs one."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Name) and func.id in _METRIC_TYPES:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in _METRIC_TYPES:
        return func.attr
    return None


class MetricRegistration:
    name = "metric-registration"
    description = ("metrics are declared once in metrics.py, snake_case, "
                   "with the conventional unit suffix")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        registry_src = None
        declared: set = set()
        for src in sources:
            if src.name == "metrics.py" and len(src.relparts) == 1:
                registry_src = src
        if registry_src is not None:
            declared = {
                t.id
                for node in registry_src.tree.body
                if isinstance(node, ast.Assign)
                for t in node.targets
                if isinstance(t, ast.Name)
            }
            yield from self._check_registry(registry_src)
            instances = self._metric_instances(registry_src)
            yield from self._check_coverage(
                registry_src, "reset_all", instances,
                "not reset by reset_all() — its value would leak across "
                "test/bench runs")
            for src in sources:
                yield from self._check_coverage(
                    src, "prometheus_text", instances,
                    "absent from the Prometheus exposition — it would "
                    "never export")
        for src in sources:
            yield from self._check_module(src, registry_src, declared)

    @staticmethod
    def _metric_instances(registry_src: SourceFile) -> dict:
        """{instance variable name: line} for every module-level metric
        declaration in the registry."""
        out: dict = {}
        for node in registry_src.tree.body:
            if isinstance(node, ast.Assign) and \
                    _metric_ctor(node.value) is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.lineno
        return out

    def _check_coverage(self, src: SourceFile, fn_name: str,
                        instances: dict, why: str) -> Iterator[Finding]:
        """Every declared metric must be referenced inside ``fn_name``
        (by bare name or as ``metrics.X``) — unless the function calls
        ``all_metrics()``, which makes it registry-driven and exhaustive
        by construction."""
        fn = next((node for node in src.tree.body
                   if isinstance(node, ast.FunctionDef)
                   and node.name == fn_name), None)
        if fn is None:
            return
        referenced: set = set()
        registry_driven = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                referenced.add(node.id)
            elif isinstance(node, ast.Attribute):
                referenced.add(node.attr)
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func) or ""
                if dotted.split(".")[-1] == "all_metrics":
                    registry_driven = True
        if registry_driven:
            return
        for name in sorted(set(instances) - referenced):
            yield Finding(
                self.name, src.path, fn.lineno,
                f"metric `{name}` is declared in metrics.py but {why}; "
                f"enumerate it in {fn_name}() or iterate all_metrics()")

    def _check_registry(self, src: SourceFile) -> Iterator[Finding]:
        seen: dict = {}
        for node in src.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            kind = _metric_ctor(node.value)
            if kind is None:
                continue
            call = node.value
            if not call.args or not isinstance(call.args[0], ast.Constant) \
                    or not isinstance(call.args[0].value, str):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"{kind} declared without a literal name — the "
                    f"registry must be greppable")
                continue
            metric_name = call.args[0].value
            if metric_name in seen:
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"metric name `{metric_name}` declared twice (first at "
                    f"line {seen[metric_name]})")
            seen[metric_name] = node.lineno
            if not _SNAKE_RE.match(metric_name):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"metric name `{metric_name}` is not snake_case")
                continue
            if kind in ("Counter", "LabeledCounter") and \
                    not metric_name.endswith("_total"):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"counter `{metric_name}` must end in `_total`")
            if kind == "Histogram" and \
                    not metric_name.endswith(_HISTOGRAM_UNITS):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"histogram `{metric_name}` needs a unit suffix "
                    f"({', '.join(_HISTOGRAM_UNITS)})")

    def _check_module(self, src: SourceFile,
                      registry_src: SourceFile | None,
                      declared: set) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            kind = _metric_ctor(node)
            if kind is not None and src is not registry_src:
                # metric classes may be *defined* anywhere (fixtures,
                # forks of the registry), but instances live in metrics.py
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"{kind} instantiated outside metrics.py — declare it "
                    f"in the registry so the name exists exactly once")
            if registry_src is not None and isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "metrics" and node.attr.isupper() \
                    and node.attr not in declared:
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"`metrics.{node.attr}` is not declared in metrics.py "
                    f"— emitting an unregistered metric")

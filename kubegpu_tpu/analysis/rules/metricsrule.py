"""metric-registration: one metrics registry, consistent names.

Every metric the system emits is declared exactly once, at module level,
in ``metrics.py`` — so dashboards have one place to discover names and a
renamed metric cannot half-exist. Names are snake_case; counters carry
the ``_total`` suffix and histograms a unit suffix, Prometheus-style.
Modules emit through the declared module-level objects
(``metrics.EVICTIONS.inc()``); referencing an undeclared ``metrics.X``
is a typo that would otherwise surface as an AttributeError mid-flight.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from kubegpu_tpu.analysis.engine import Context, Finding, SourceFile

_METRIC_TYPES = frozenset({"Counter", "Gauge", "Histogram"})
_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HISTOGRAM_UNITS = ("_microseconds", "_milliseconds", "_seconds", "_us",
                    "_ms", "_bytes", "_total")


def _metric_ctor(node: ast.AST) -> str | None:
    """'Counter'/'Gauge'/'Histogram' when ``node`` constructs one."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Name) and func.id in _METRIC_TYPES:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in _METRIC_TYPES:
        return func.attr
    return None


class MetricRegistration:
    name = "metric-registration"
    description = ("metrics are declared once in metrics.py, snake_case, "
                   "with the conventional unit suffix")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        registry_src = None
        declared: set = set()
        for src in sources:
            if src.name == "metrics.py" and len(src.relparts) == 1:
                registry_src = src
        if registry_src is not None:
            declared = {
                t.id
                for node in registry_src.tree.body
                if isinstance(node, ast.Assign)
                for t in node.targets
                if isinstance(t, ast.Name)
            }
            yield from self._check_registry(registry_src)
        for src in sources:
            yield from self._check_module(src, registry_src, declared)

    def _check_registry(self, src: SourceFile) -> Iterator[Finding]:
        seen: dict = {}
        for node in src.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            kind = _metric_ctor(node.value)
            if kind is None:
                continue
            call = node.value
            if not call.args or not isinstance(call.args[0], ast.Constant) \
                    or not isinstance(call.args[0].value, str):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"{kind} declared without a literal name — the "
                    f"registry must be greppable")
                continue
            metric_name = call.args[0].value
            if metric_name in seen:
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"metric name `{metric_name}` declared twice (first at "
                    f"line {seen[metric_name]})")
            seen[metric_name] = node.lineno
            if not _SNAKE_RE.match(metric_name):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"metric name `{metric_name}` is not snake_case")
                continue
            if kind == "Counter" and not metric_name.endswith("_total"):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"counter `{metric_name}` must end in `_total`")
            if kind == "Histogram" and \
                    not metric_name.endswith(_HISTOGRAM_UNITS):
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"histogram `{metric_name}` needs a unit suffix "
                    f"({', '.join(_HISTOGRAM_UNITS)})")

    def _check_module(self, src: SourceFile,
                      registry_src: SourceFile | None,
                      declared: set) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            kind = _metric_ctor(node)
            if kind is not None and src is not registry_src:
                # metric classes may be *defined* anywhere (fixtures,
                # forks of the registry), but instances live in metrics.py
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"{kind} instantiated outside metrics.py — declare it "
                    f"in the registry so the name exists exactly once")
            if registry_src is not None and isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "metrics" and node.attr.isupper() \
                    and node.attr not in declared:
                yield Finding(
                    self.name, src.path, node.lineno,
                    f"`metrics.{node.attr}` is not declared in metrics.py "
                    f"— emitting an unregistered metric")

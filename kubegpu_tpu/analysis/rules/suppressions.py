"""Unused-suppression audit: stale ``# analysis: disable=`` comments.

A suppression that no longer silences anything is itself a finding —
the invariant it waived may have been fixed (so the waiver should go),
or the rule moved and the comment now silences *nothing* while looking
like it silences *something*. Same stance as ruff's unused-``noqa``.

The engine marks every suppression with the rules it actually silenced
during this invocation; this rule (always run last) flags:

- a suppression naming a rule that RAN and silenced nothing,
- a suppression naming a rule that does not exist (typo'd waivers are
  silently-broken waivers),
- an ``all`` wildcard that silenced nothing (audited only when every
  rule ran — a partial ``--select`` cannot prove it dead).

Suppressions naming rules excluded by ``--select`` are left alone: the
evidence to audit them was not collected.

Rules with their own waiver grammar register usage evidence in
``ctx.waiver_audits`` (host-sync's ``# host-sync: allowed -- why``);
those waivers are audited here under the same gate: only when the
owning rule ran, a waiver covering no boundary call is stale.
"""

from __future__ import annotations

from typing import Iterator

from kubegpu_tpu.analysis.engine import Context, Finding


class UnusedSuppression:
    name = "unused-suppression"
    description = ("`# analysis: disable=` comments that no longer "
                   "suppress anything (or name unknown rules) are "
                   "findings, like ruff's unused-noqa")

    def run(self, sources: list, ctx: Context) -> Iterator[Finding]:
        ran = set(ctx.ran_rules) - {self.name}
        known = set(ctx.known_rules)
        full_run = known - {self.name} <= ran
        for src in sources:
            for sup in src.suppressions:
                for rule in sorted(sup.rules):
                    if rule == self.name:
                        continue  # waiving this audit is always "used"
                    if rule == "all":
                        if full_run and not sup.used_rules:
                            yield Finding(
                                self.name, src.path, sup.line,
                                "suppression `all` no longer suppresses "
                                "anything; remove it")
                        continue
                    if rule not in known:
                        yield Finding(
                            self.name, src.path, sup.line,
                            f"suppression names unknown rule `{rule}` "
                            f"(typo? removed rule?); it silences nothing")
                        continue
                    if rule in ran and rule not in sup.used_rules:
                        yield Finding(
                            self.name, src.path, sup.line,
                            f"suppression of `{rule}` no longer "
                            f"suppresses anything here; remove it (the "
                            f"waived invariant may have been fixed)")
        for rule, audits in sorted(ctx.waiver_audits.items()):
            if rule not in ran:
                continue  # no evidence collected this invocation
            for audit in audits:
                if not audit["used"]:
                    yield Finding(
                        self.name, audit["path"], audit["line"],
                        f"`# {rule}: allowed` waiver no longer covers a "
                        "boundary call; remove it (the waived sync may "
                        "have been fixed)")

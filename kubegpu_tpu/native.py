"""ctypes bridge to the native library (enumerator + contiguous search).

Build with ``make -C native`` (g++, no external deps). Everything here is
optional: each caller has a pure-Python fallback, and
``KUBEGPU_TPU_NATIVE=0`` disables the native path entirely. The Python
implementations remain the semantic reference; the native ones are
differentially tested against them.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
LIB_PATH = os.path.join(NATIVE_DIR, "build", "libkubegpu_tpu_native.so")

_lib = None
_lib_tried = False


def build_native(force: bool = False) -> str | None:
    """Compile the native library; returns its path or None on failure."""
    if force:
        subprocess.run(["make", "-C", NATIVE_DIR, "clean"],
                       capture_output=True, check=False)
    proc = subprocess.run(["make", "-C", NATIVE_DIR], capture_output=True,
                          text=True, check=False)
    if proc.returncode != 0 or not os.path.exists(LIB_PATH):
        return None
    global _lib, _lib_tried
    _lib, _lib_tried = None, False  # reload on next use
    return LIB_PATH


def get_lib():
    """The loaded native library, or None when unavailable/disabled."""
    global _lib, _lib_tried
    if os.environ.get("KUBEGPU_TPU_NATIVE", "1") == "0":
        return None
    if _lib_tried:
        return _lib
    # racer: single-writer -- idempotent lazy-init latch under the GIL;
    # a racing duplicate load resolves to the same library
    _lib_tried = True
    if not os.path.exists(LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(LIB_PATH)
        lib.tpu_enumerate.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_int]
        lib.tpu_enumerate.restype = ctypes.c_int
        lib.tpu_last_error.restype = ctypes.c_char_p
        lib.tpu_find_contiguous_block.argtypes = [
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.tpu_find_contiguous_block.restype = ctypes.c_int
        try:
            lib.grp_allocate.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                         ctypes.c_int]
            lib.grp_allocate.restype = ctypes.c_int
            lib.grp_last_error.restype = ctypes.c_char_p
        except AttributeError:
            pass  # stale library without the allocator core
        try:
            lib.dl_open.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                    ctypes.c_longlong, ctypes.c_ulonglong,
                                    ctypes.c_int]
            lib.dl_open.restype = ctypes.c_void_p
            lib.dl_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_longlong]
            lib.dl_next.restype = ctypes.c_longlong
            lib.dl_close.argtypes = [ctypes.c_void_p]
            lib.dl_last_error.restype = ctypes.c_char_p
        except AttributeError:
            pass  # stale library without the data loader
        # racer: single-writer -- idempotent lazy init (see _lib_tried)
        _lib = lib
    except OSError:
        _lib = None  # racer: single-writer -- idempotent lazy init
    return _lib


def native_enumerate(sysfs_root: str) -> dict:
    """Run the C++ enumerator over a sysfs-style tree; returns the parsed
    inventory JSON. Raises RuntimeError with the shim's error message."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library not built (make -C native)")
    buf = ctypes.create_string_buffer(1 << 20)
    n = lib.tpu_enumerate(sysfs_root.encode(), buf, len(buf))
    if n < 0:
        raise RuntimeError(
            f"tpu_enumerate failed: {lib.tpu_last_error().decode()}")
    return json.loads(buf.value.decode())


def native_grp_allocate(payload: str) -> str:
    """Run the native group-allocation search. ``payload``/result use the
    line protocol documented in `native/grpalloc.cpp`. Raises RuntimeError
    when the library is missing or the call fails (callers fall back to
    the Python implementation)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "grp_allocate"):
        raise RuntimeError("native allocator not built (make -C native)")
    cap = max(1 << 16, 4 * len(payload) + 4096)
    buf = ctypes.create_string_buffer(cap)
    n = lib.grp_allocate(payload.encode(), buf, cap)
    if n == -2:  # output larger than the buffer: retry once, bigger
        cap *= 16
        buf = ctypes.create_string_buffer(cap)
        n = lib.grp_allocate(payload.encode(), buf, cap)
    if n < 0:
        raise RuntimeError(
            f"grp_allocate failed: {lib.grp_last_error().decode()}")
    return buf.value.decode()


def native_find_contiguous_block(dims, wrap, free_coords, count):
    """Native contiguous-block search; returns sorted coord list, None when
    impossible, or raises RuntimeError if the library is unavailable."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library not built")
    free_list = sorted(map(tuple, free_coords))
    dims_a = (ctypes.c_int * 3)(*dims)
    wrap_a = (ctypes.c_int * 3)(*(1 if w else 0 for w in wrap))
    flat = [c for coord in free_list for c in coord]
    free_a = (ctypes.c_int * max(1, len(flat)))(*flat) if flat else \
        (ctypes.c_int * 1)(0)
    out_a = (ctypes.c_int * max(1, count * 3))()
    n = lib.tpu_find_contiguous_block(dims_a, wrap_a, free_a,
                                      len(free_list), count, out_a)
    if n < 0:
        return None
    return sorted(tuple(out_a[3 * i + j] for j in range(3)) for i in range(n))

"""A mock Kubernetes API server speaking the real wire grammar.

The reference tests its node side against a fake nvidia-docker REST
daemon returning canned JSON (`nvidia_fake_plugin.go:29-39`); this is the
same seam one level up — a real HTTP server with genuine Kubernetes
paths, verbs, patch content-types, Binding subresource, and streaming
``?watch=true`` JSON-lines, backed by `InMemoryAPIServer` semantics. It
exists so `KubeAPIClient` (cluster/kubeclient.py) is tested against the
grammar it will meet in production, not against a convenience API.

Not a complete kube-apiserver: only the resources/verbs this framework
uses (SURVEY.md §1 — annotations and bind ARE the wire protocol).
"""

from __future__ import annotations

import copy
import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubegpu_tpu.cluster.apiserver import Conflict, InMemoryAPIServer, NotFound

STRATEGIC_MERGE = "application/strategic-merge-patch+json"
_EVENT_TYPES = {"added": "ADDED", "modified": "MODIFIED",
                "deleted": "DELETED"}


class _VersionedLog:
    """Sequence-numbered event log; the seq doubles as resourceVersion."""

    def __init__(self, api: InMemoryAPIServer, limit: int = 10000):
        self._cond = threading.Condition()
        self._events: list = []  # (seq, kind, TYPE, obj)
        self.seq = 0
        self.limit = limit
        api.add_watcher(self._record)

    def _record(self, kind, event, obj):
        with self._cond:
            self.seq += 1
            obj = copy.deepcopy(obj)
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.seq)
            self._events.append((self.seq, kind, _EVENT_TYPES[event], obj))
            if len(self._events) > self.limit:
                self._events = self._events[-self.limit:]
            self._cond.notify_all()

    def wait_since(self, seq: int, timeout: float = 5.0):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                out = [e for e in self._events if e[0] > seq]
                if out or time.monotonic() >= deadline:
                    return out
                self._cond.wait(min(0.5, deadline - time.monotonic()))


def serve_mock_kube(api: InMemoryAPIServer | None = None,
                    host: str = "127.0.0.1", port: int = 0,
                    token: str | None = None, namespace: str = "default"):
    """Serve; returns (server, base_url, api). Daemon thread; stop with
    ``server.shutdown()``. ``token`` (optional) enforces Bearer auth."""
    api = api or InMemoryAPIServer()
    log = _VersionedLog(api)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        # -- plumbing -------------------------------------------------------

        def _body(self):
            n = int(self.headers.get("Content-Length") or 0)
            return json.loads(self.rfile.read(n).decode()) if n else {}

        def _send(self, code: int, obj=None):
            data = json.dumps(obj if obj is not None else {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _authorized(self) -> bool:
            if token is None:
                return True
            return self.headers.get("Authorization") == f"Bearer {token}"

        def _parse(self):
            path, _, rawq = self.path.partition("?")
            parts = [urllib.parse.unquote(p) for p in path.split("/") if p]
            query = {k: v[0] for k, v in
                     urllib.parse.parse_qs(rawq).items()}
            return parts, query

        def _route(self, method: str):
            if not self._authorized():
                return self._send(401, {"kind": "Status", "code": 401,
                                        "message": "Unauthorized"})
            parts, query = self._parse()
            try:
                return self._dispatch(method, parts, query)
            except NotFound as e:
                self._send(404, {"kind": "Status", "code": 404,
                                 "reason": "NotFound", "message": str(e)})
            except Conflict as e:
                self._send(409, {"kind": "Status", "code": 409,
                                 "reason": "Conflict", "message": str(e)})
            except BrokenPipeError:
                pass
            except Exception as e:  # noqa: BLE001
                self._send(500, {"kind": "Status", "code": 500,
                                 "message": f"{type(e).__name__}: {e}"})

        # -- watch streaming ------------------------------------------------

        def _stream_watch(self, kind: str, since: int):
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            seq = since
            while True:
                events = log.wait_since(seq, timeout=5.0)
                for s, k, typ, obj in events:
                    seq = max(seq, s)
                    if k != kind:
                        continue
                    frame = json.dumps(
                        {"type": typ, "object": obj}).encode() + b"\n"
                    self.wfile.write(
                        f"{len(frame):x}\r\n".encode() + frame + b"\r\n")
                    self.wfile.flush()

        # -- dispatch -------------------------------------------------------

        def _list(self, kind_name: str, items: list):
            self._send(200, {
                "apiVersion": "v1", "kind": kind_name,
                "metadata": {"resourceVersion": str(log.seq)},
                "items": items})

        def _require_smp(self):
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            if ctype != STRATEGIC_MERGE:
                raise Conflict(f"unsupported patch content-type {ctype!r}; "
                               f"want {STRATEGIC_MERGE}")

        def _dispatch(self, method, parts, query):
            if parts[:2] != ["api", "v1"]:
                return self._send(404, {"kind": "Status", "code": 404,
                                        "message": "unknown API group"})
            rest = parts[2:]

            # /api/v1/nodes[...]
            if rest and rest[0] == "nodes":
                if len(rest) == 1:
                    if method == "GET" and query.get("watch") == "true":
                        return self._stream_watch(
                            "node", int(query.get("resourceVersion") or 0))
                    if method == "GET":
                        return self._list("NodeList", api.list_nodes())
                    if method == "POST":
                        return self._send(201, api.create_node(self._body()))
                elif len(rest) == 2:
                    name = rest[1]
                    if method == "GET":
                        return self._send(200, api.get_node(name))
                    if method == "DELETE":
                        api.delete_node(name)
                        return self._send(200, {"kind": "Status", "code": 200})
                    if method == "PATCH":
                        self._require_smp()
                        patch = self._body()
                        return self._send(200, api.patch_node_metadata(
                            name, patch.get("metadata") or {}))

            # /api/v1/namespaces/{ns}/pods[...]
            if (len(rest) >= 3 and rest[0] == "namespaces"
                    and rest[1] == namespace and rest[2] == "pods"):
                sub = rest[3:]
                if not sub:
                    if method == "GET" and query.get("watch") == "true":
                        return self._stream_watch(
                            "pod", int(query.get("resourceVersion") or 0))
                    if method == "GET":
                        node = None
                        sel = query.get("fieldSelector") or ""
                        if sel.startswith("spec.nodeName="):
                            node = sel.split("=", 1)[1]
                        return self._list("PodList", api.list_pods(node))
                    if method == "POST":
                        return self._send(201, api.create_pod(self._body()))
                elif len(sub) == 1:
                    name = sub[0]
                    if method == "GET":
                        return self._send(200, api.get_pod(name))
                    if method == "DELETE":
                        api.delete_pod(name)
                        return self._send(200, {"kind": "Status", "code": 200})
                    if method == "PATCH":
                        self._require_smp()
                        patch = self._body()
                        ann = ((patch.get("metadata") or {})
                               .get("annotations"))
                        if ann is None:
                            raise Conflict("only annotation patches modeled")
                        return self._send(
                            200, api.update_pod_annotations(name, ann))
                elif sub[1:] == ["binding"] and method == "POST":
                    binding = self._body()
                    if binding.get("kind") != "Binding":
                        raise Conflict("body must be a v1 Binding")
                    api.bind_pod(sub[0], (binding.get("target") or {})["name"])
                    return self._send(201, {"kind": "Status", "code": 201})

            # /api/v1/persistentvolumes[...] (cluster-scoped)
            if rest and rest[0] == "persistentvolumes":
                if len(rest) == 1:
                    if method == "GET" and query.get("watch") == "true":
                        return self._stream_watch(
                            "pv", int(query.get("resourceVersion") or 0))
                    if method == "GET":
                        return self._list("PersistentVolumeList",
                                          api.list_pvs())
                    if method == "POST":
                        return self._send(201, api.create_pv(self._body()))
                elif len(rest) == 2:
                    name = rest[1]
                    if method == "GET":
                        return self._send(200, api.get_pv(name))
                    if method == "DELETE":
                        api.delete_pv(name)
                        return self._send(200, {"kind": "Status", "code": 200})
                    if method == "PATCH":
                        self._require_smp()
                        return self._send(200, api.patch_pv_spec(
                            name, self._body().get("spec") or {}))

            # /api/v1/namespaces/{ns}/persistentvolumeclaims[...]
            if (len(rest) >= 3 and rest[0] == "namespaces"
                    and rest[1] == namespace
                    and rest[2] == "persistentvolumeclaims"):
                sub = rest[3:]
                if not sub:
                    if method == "GET" and query.get("watch") == "true":
                        return self._stream_watch(
                            "pvc", int(query.get("resourceVersion") or 0))
                    if method == "GET":
                        return self._list("PersistentVolumeClaimList",
                                          api.list_pvcs())
                    if method == "POST":
                        return self._send(201, api.create_pvc(self._body()))
                elif len(sub) == 1:
                    name = sub[0]
                    if method == "GET":
                        return self._send(200, api.get_pvc(name))
                    if method == "DELETE":
                        api.delete_pvc(name)
                        return self._send(200, {"kind": "Status", "code": 200})
                    if method == "PATCH":
                        self._require_smp()
                        return self._send(200, api.patch_pvc_spec(
                            name, self._body().get("spec") or {}))

            self._send(404, {"kind": "Status", "code": 404,
                             "message": f"no route {method} {self.path}"})

        def do_GET(self):
            self._route("GET")

        def do_POST(self):
            self._route("POST")

        def do_PATCH(self):
            self._route("PATCH")

        def do_DELETE(self):
            self._route("DELETE")

    class Server(ThreadingHTTPServer):
        daemon_threads = True

        def shutdown(self):
            # stopped means STOPPED: serve_forever has returned by the
            # time super().shutdown() comes back, so the listening
            # socket is released here instead of leaking until process
            # exit (same lifecycle contract as cluster/httpapi.py)
            super().shutdown()
            self.server_close()

    server = Server((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="mock-kube-apiserver").start()
    return server, f"http://{host}:{server.server_address[1]}", api

"""Full-duplex framed stream transport for the control plane.

The JSON wire pays one HTTP header parse + one JSON encode/decode per
round trip and a long-poll re-request per watch batch; at fleet scale
that framing cost IS the apiserver's ceiling. This module replaces the
framing under the same client surface: after an HTTP ``Upgrade:
kgtpu-stream`` handshake on the existing keep-alive socket, both ends
speak length-prefixed, CRC-checked frames (the same record discipline
``cluster/wal.py`` uses on disk) multiplexing requests, responses, and
server-pushed watch deltas. Payloads ride the compact binary codec in
``core/codec.py``.

Frame layout (little-endian), mirroring the WAL record:

    [1B type][4B request id][4B payload length][4B CRC32(payload)][payload]

Types::

    REQ   client -> server   codec.encode_request payload; the id is
                             echoed by the matching RESP
    RESP  server -> client   codec.encode_response payload
    SUB   client -> server   watch subscription {since, kinds, batch};
                             acked by a RESP, then deltas arrive as PUSH
    PUSH  server -> client   codec.encode_watch_batch payload, id 0 —
                             unsolicited; this is what retires the
                             long-poll re-request per batch
    PING  either direction   liveness; empty payload, never acked
    REJECT server -> client  flow control: the priority-&-fairness
                             front door shed the request; the payload
                             is a 429 response carrying retry_after_s,
                             echoed with the REQ's id

A torn, corrupt, oversized, or out-of-protocol frame poisons exactly ONE
connection: the reader raises :class:`FrameError` (a ``ConnectionError``,
so the client's idempotent-retry and watch-reconnect layers treat it as
the transport fault it is), both ends drop the socket, and the client
reconnects and resumes — requests through the retry policy, watch
seq-exact from its cursor. Nothing is ever re-synchronized inside a
damaged stream.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import urllib.parse
import zlib
from typing import Any, Callable, Optional, Tuple

from kubegpu_tpu import metrics
from kubegpu_tpu.analysis.explore import probe
from kubegpu_tpu.core import codec

_HEADER = struct.Struct("<BIII")  # type, request id, length, CRC32

# Frame types.
REQ = 1
RESP = 2
SUB = 3
PUSH = 4
PING = 5
# Flow control: the front door (cluster/apf.py) shed this request. The
# payload is an encode_response(429, body) whose body carries the
# advised retry_after_s — a first-class frame type (not a RESP) so
# back-pressure is distinguishable at the framing layer, mirroring the
# HTTP 429 the JSON wire sends.
REJECT = 6

_FRAME_TYPES = frozenset({REQ, RESP, SUB, PUSH, PING, REJECT})

# One frame must fit a full list response for a 4k-node fleet with slack;
# anything larger is a protocol violation, not a workload.
MAX_FRAME = 128 * 1024 * 1024

UPGRADE_PATH = "/stream"
UPGRADE_TOKEN = "kgtpu-stream"
WIRE_STREAM = "stream"
WIRE_JSON = "json"
# transport_bytes_total{wire} attribution for the proxy -> apiserver
# hop (cluster/proxy.py): same framing as WIRE_STREAM, counted apart so
# a fronted deployment's upstream leg is measurable on its own
WIRE_PROXY = "proxy"


class FrameError(ConnectionError):
    """The stream is no longer frame-aligned (torn/corrupt/oversized or
    unexpected frame): the CONNECTION is unrecoverable and must be
    dropped. A ``ConnectionError`` on purpose — every retry/reconnect
    layer already classifies that as a transport fault."""


class StreamClosed(ConnectionError):
    """Clean EOF at a frame boundary (peer went away)."""


class StreamUnsupported(Exception):
    """The server answered the upgrade with a normal HTTP response — an
    older JSON-only server. The client negotiates down to the JSON wire;
    this is the one handshake failure that must NOT look like a
    transport fault (nothing is broken, the capability is absent)."""


def encode_frame(ftype: int, rid: int, payload: bytes) -> bytes:
    return _HEADER.pack(ftype, rid, len(payload),
                        zlib.crc32(payload)) + payload


def read_frame(rfile: Any, wire: str = WIRE_STREAM) -> Tuple[int, int, bytes]:
    """Read one frame off a buffered reader; raises :class:`StreamClosed`
    on clean EOF, :class:`FrameError` on anything torn or hostile."""
    probe("stream.read_frame")
    header = rfile.read(_HEADER.size)
    if not header:
        raise StreamClosed("stream closed")
    if len(header) < _HEADER.size:
        raise FrameError("truncated frame header")
    ftype, rid, length, crc = _HEADER.unpack(header)
    if ftype not in _FRAME_TYPES:
        raise FrameError(f"unknown frame type 0x{ftype:02x}")
    if length > MAX_FRAME:
        raise FrameError(f"oversized frame ({length} bytes)")
    payload = rfile.read(length)
    if len(payload) < length:
        raise FrameError("truncated frame payload")
    if zlib.crc32(payload) != crc:
        raise FrameError("frame CRC mismatch")
    metrics.TRANSPORT_BYTES.labels(wire, "rx").inc(
        _HEADER.size + length)
    return ftype, rid, payload


def send_frame(sock: socket.socket, wlock: threading.Lock, ftype: int,
               rid: int, payload: bytes,
               wire: str = WIRE_STREAM) -> None:
    """Write one frame atomically w.r.t. other writers on this socket
    (responses and pushes interleave on the server side)."""
    send_raw(sock, wlock, encode_frame(ftype, rid, payload), wire=wire)


def send_raw(sock: socket.socket, wlock: threading.Lock,
             data: bytes, wire: str = WIRE_STREAM) -> None:
    probe("stream.send_frame")
    with wlock:
        sock.sendall(data)
    metrics.TRANSPORT_BYTES.labels(wire, "tx").inc(len(data))


def _timed(hist: Any, fn: Callable[..., Any], *args: Any) -> Any:
    t0 = time.perf_counter()
    out = fn(*args)
    hist.observe((time.perf_counter() - t0) * 1e3)
    return out


def _decode(fn: Callable[[bytes], Any], data: bytes) -> Any:
    """Decode a frame payload; a codec rejection means the CONNECTION is
    no longer speaking the protocol (the bytes passed CRC, so this is a
    peer/codec asymmetry, not line noise) — surface it as the same typed
    transport fault every torn frame raises."""
    try:
        return fn(data)
    except codec.CodecError as e:
        raise FrameError(f"undecodable frame payload: {e}") from e


class StreamConn:
    """Client side of one framed connection.

    A connection serves EITHER serialized request/response round trips
    (`request`; one outstanding at a time, per-thread like the HTTP
    keep-alive sockets it replaces) OR a watch subscription
    (`subscribe` + `read_push`). Both directions carry the per-frame
    interned binary codec; any framing fault closes the socket and
    surfaces as a ``ConnectionError`` for the caller's retry layer.
    """

    def __init__(self, sock: socket.socket,
                 label: Optional[str] = None) -> None:
        self._sock = sock
        self._rfile = sock.makefile("rb")
        # byte-attribution label for this connection's frames (the
        # proxy's upstream leg counts as WIRE_PROXY, everything else as
        # the stream wire it is)
        self._label = label or WIRE_STREAM
        self._wlock = threading.Lock()
        # racer: single-writer -- a StreamConn serves one requesting
        # thread at a time (per-thread keep-alive contract)
        self._rid = 0
        # racer: single-writer -- one-way latch: close() may race the
        # owner but every writer stores True
        self.closed = False

    @classmethod
    def connect(cls, base_url: str, timeout: float,
                label: Optional[str] = None) -> "StreamConn":
        """Dial + upgrade. Raises :class:`StreamUnsupported` when the
        server speaks only JSON HTTP (negotiated fallback), ordinary
        ``OSError``/``ConnectionError`` on real transport faults."""
        split = urllib.parse.urlsplit(base_url)
        host = split.hostname or "127.0.0.1"
        port = split.port or 80
        sock = socket.create_connection((host, port), timeout=timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            request = (f"GET {UPGRADE_PATH} HTTP/1.1\r\n"
                       f"Host: {host}:{port}\r\n"
                       f"Connection: Upgrade\r\n"
                       f"Upgrade: {UPGRADE_TOKEN}\r\n\r\n").encode()
            sock.sendall(request)
            status, headers = _read_http_head(sock)
            if status != 101 or \
                    headers.get("upgrade", "").lower() != UPGRADE_TOKEN:
                raise StreamUnsupported(
                    f"server answered upgrade with HTTP {status}")
        except BaseException:
            sock.close()
            raise
        return cls(sock, label=label)

    def request(self, method: str, path: str, body: object,
                timeout: float,
                trace: Optional[str] = None) -> Tuple[int, object]:
        """One round trip; returns ``(status, decoded body)``. Any frame
        or transport fault closes the connection and re-raises — the
        caller reconnects (and may retry per its idempotency policy)."""
        self._rid += 1
        rid = self._rid
        payload = _timed(metrics.FRAME_ENCODE_MS, codec.encode_request,
                         method, path, body, trace)
        try:
            self._sock.settimeout(timeout)
            send_frame(self._sock, self._wlock, REQ, rid, payload,
                       wire=self._label)
            while True:
                ftype, got_rid, data = read_frame(self._rfile,
                                                 wire=self._label)
                if ftype == PING:
                    continue
                if ftype == REJECT and got_rid == rid:
                    # flow control: the front door shed this request;
                    # the payload is a (429, body) response whose body
                    # advises retry_after_s — surfaced through the same
                    # status path as the JSON wire so the caller's
                    # typed-error reconstruction is wire-agnostic
                    return _timed(metrics.FRAME_DECODE_MS, _decode,
                                  codec.decode_response, data)
                if ftype != RESP or got_rid != rid:
                    raise FrameError(
                        f"unexpected frame type {ftype} rid {got_rid} "
                        f"while waiting for response {rid}")
                return _timed(metrics.FRAME_DECODE_MS, _decode,
                              codec.decode_response, data)
        except BaseException:
            self.close()
            raise

    def subscribe(self, since: int, kinds: Optional[Tuple[str, ...]],
                  batch_s: float, timeout: float) -> dict:
        """Register this connection as a push watcher; returns the ack
        ``{"seq", "epoch"}``. Deltas then arrive via :meth:`read_push`."""
        self._rid += 1
        rid = self._rid
        payload = codec.encode_value(
            {"since": since, "kinds": list(kinds) if kinds else None,
             "batch": batch_s})
        try:
            self._sock.settimeout(timeout)
            send_frame(self._sock, self._wlock, SUB, rid, payload,
                       wire=self._label)
            while True:
                ftype, got_rid, data = read_frame(self._rfile,
                                                 wire=self._label)
                if ftype == PING:
                    continue
                if ftype != RESP or got_rid != rid:
                    raise FrameError("unexpected frame during subscribe")
                status, body = _decode(codec.decode_response, data)
                if status != 200 or not isinstance(body, dict):
                    raise FrameError(f"subscribe refused: HTTP {status}")
                return body
        except BaseException:
            self.close()
            raise

    def read_push(self, timeout: float) -> Optional[dict]:
        """Next pushed watch batch (decoded), or None for a liveness
        PING. Socket timeout / frame faults propagate as
        ``ConnectionError`` after closing the connection."""
        try:
            self._sock.settimeout(timeout)
            ftype, _rid, data = read_frame(self._rfile,
                                            wire=self._label)
            if ftype == PING:
                return None
            if ftype != PUSH:
                raise FrameError(f"unexpected frame type {ftype} on "
                                 f"watch connection")
            return _timed(metrics.FRAME_DECODE_MS, _decode,
                          codec.decode_watch_batch, data)
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        self.closed = True
        try:
            # a reader blocked in recv() does NOT wake when another
            # thread close()s the fd — it would sit there until the
            # server's next liveness ping. shutdown() interrupts it NOW
            # (EOF at the socket layer), which is what makes close()
            # from a lifecycle path (client.close, proxy.stop) prompt
            # instead of one ping period late.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            # the makefile reader holds an io-ref on the socket: without
            # closing it the OS fd survives sock.close() until GC — the
            # per-test leak guard's first catch
            self._rfile.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def _read_http_head(sock: socket.socket) -> Tuple[int, dict]:
    """Status + lowercased headers of the upgrade reply, reading byte
    groups until the blank line (no body follows a 101; for any other
    status we only need the status code before falling back)."""
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionError("connection closed during upgrade")
        data += chunk
        if len(data) > 65536:
            raise FrameError("oversized upgrade response")
    head = data.split(b"\r\n\r\n", 1)[0].decode("latin-1")
    lines = head.split("\r\n")
    parts = lines[0].split(None, 2)
    if len(parts) < 2 or not parts[1].isdigit():
        raise FrameError(f"malformed upgrade response line: {lines[0]!r}")
    headers = {}
    for line in lines[1:]:
        if ":" in line:
            key, val = line.split(":", 1)
            headers[key.strip().lower()] = val.strip()
    return int(parts[1]), headers

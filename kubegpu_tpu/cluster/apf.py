"""API priority & fairness: the control plane's multi-tenant front door.

One abusive tenant's pod-create flood must not starve heartbeats, lease
renewals, or watch traffic — "millions of users" means many tenants
hammering ONE apiserver, and without admission discipline the slowest
consumer sets everyone's latency. This module is the request-
classification and fair-queuing layer both wire framings dispatch
through (``cluster/httpapi.py`` wraps the shared ``_route_request``
route table in :meth:`APFDispatcher.admit`), modeled on upstream
kube-apiserver's API Priority & Fairness:

* every request is classified into a **flow** (the tenant from pod
  labels/annotations when the body carries one, else the client's
  identity) and a **priority band**;
* the ``system`` band — heartbeat patches, leases, watch/SUB, health,
  debug — is EXEMPT: never queued, never rejected, so control traffic
  survives any flood by construction;
* every other band has bounded concurrency (seats), per-band
  **shuffle-sharded queues** (each flow hashes to a small deterministic
  hand of queues and enqueues into the shortest, so an abusive flow
  saturates its own hand while most well-behaved flows keep a clean
  queue), and a **queue-wait deadline**;
* work that cannot be seated in time is rejected with a typed
  :class:`TooManyRequests` carrying ``retry_after_s`` — HTTP 429 on the
  JSON wire, a flow-control REJECT frame on the stream wire — and the
  client's idempotent-retry policy honors the advised backoff.

The dispatcher is transport-neutral and deliberately knows nothing
about the route table beyond path shapes; the scheduler-side half of
tenancy (dominant-resource chip quotas) lives in
``scheduler/quota.py`` and shares the tenant helpers below.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple

from kubegpu_tpu import metrics
from kubegpu_tpu.analysis.explore import probe
from kubegpu_tpu.core import codec, grammar

# Tenant identity on pod objects: a label (primary) or annotation
# (fallback). Pods carrying neither belong to no tenant — system pods —
# and are exempt from both the flow classifier's tenant path and the
# scheduler-side quota gate.
TENANT_LABEL = "kgtpu.io/tenant"
TENANT_ANNOTATION = "kgtpu.io/tenant"

BAND_SYSTEM = "system"
BAND_CONTROLLER = "controller"
BAND_WORKLOAD = "workload"

# First path segments that are system traffic regardless of verb:
# health, watch long-polls, lease acquire/renew/release, debug and
# metrics/profiling surfaces (observability must survive the floods it
# exists to explain).
_SYSTEM_SEGMENTS = frozenset({"healthz", "watch", "leases", "debug",
                              "metrics"})
# Control-loop write surfaces (scheduler binders, lifecycle, advertiser
# node registration, volume controllers, quota admin): above tenant
# workload, below system.
_CONTROLLER_SEGMENTS = frozenset({
    "bindmany", "podannotations", "bindvolume", "events", "nodes",
    "pvcs", "pvs", "pdbs", "quotas", "services", "rcs", "rss",
    "statefulsets"})


class TooManyRequests(RuntimeError):
    """Typed flow-control rejection: the request's band could not seat
    it within its queue-wait deadline (or its queue overflowed).
    ``retry_after_s`` is the server's advised backoff — mapped to HTTP
    429 on the JSON wire and a REJECT frame on the stream wire, and
    reconstructed typed by the client, whose idempotent-retry policy
    honors the advice. ``per_pod`` mirrors the NotFound/Conflict detail
    contract (empty here, but the error-body shape is shared)."""

    def __init__(self, message: str = "",
                 per_pod: "dict | None" = None,
                 retry_after_s: float = 0.0) -> None:
        super().__init__(message)
        self.per_pod = dict(per_pod or {})
        self.retry_after_s = float(retry_after_s)


def tenant_of_pod(pod: "dict | None") -> Optional[str]:
    """The tenant a pod object belongs to (label first, annotation as
    fallback), or None for untenanted/system pods."""
    if not isinstance(pod, dict):
        return None
    meta = pod.get("metadata") or {}
    labels = meta.get("labels") or {}
    tenant = labels.get(TENANT_LABEL) or labels.get("tenant")
    if tenant:
        return str(tenant)
    ann = meta.get("annotations") or {}
    tenant = ann.get(TENANT_ANNOTATION)
    return str(tenant) if tenant else None


def pod_chip_request(pod: "dict | None") -> int:
    """Chips a pod asks for — the quantity tenant fair share is
    measured in. Reads the device annotation's container requests
    (``alpha.tpu/numchips``), falling back to counting already-
    translated per-chip leaf requests."""
    if not isinstance(pod, dict):
        return 0
    try:
        pi = codec.kube_pod_to_pod_info(pod, invalidate_existing=False)
    except (TypeError, ValueError, KeyError):
        return 0
    total = 0
    for cont in pi.running_containers.values():
        n = int(cont.requests.get(grammar.RESOURCE_NUM_CHIPS, 0) or 0)
        if n == 0:
            n = sum(1 for res in cont.requests
                    if str(res).endswith("/" + grammar.CHIPS_SUFFIX))
        total += n
    return total


def pod_cpu_request(pod: "dict | None") -> float:
    """Core-resource CPU a pod requests (DRF's second dimension)."""
    if not isinstance(pod, dict):
        return 0.0
    total = 0.0
    for cont in (pod.get("spec") or {}).get("containers") or []:
        req = ((cont.get("resources") or {}).get("requests") or {})
        raw = req.get("cpu")
        if raw is None:
            continue
        try:
            total += float(codec.parse_quantity(raw))
        except (TypeError, ValueError):
            continue
    return total


def classify(method: str, parts: List[str],
             query: "dict | None" = None, body: object = None,
             peer: str = "") -> Tuple[str, str]:
    """``(band, flow)`` for one request. Tenant identity comes from the
    pod body when one rides the request, else the client's peer
    identity — so an abusive tenant's CREATES (the floodable verb)
    land in its own flow even when every client shares one ingress
    host; body-less verbs flow by peer, the finest identity this
    unauthenticated wire carries."""
    seg = parts[0] if parts else ""
    if seg in _SYSTEM_SEGMENTS:
        return BAND_SYSTEM, BAND_SYSTEM
    if seg == "nodes" and method == "PATCH":
        # heartbeat/inventory re-patches: the liveness signal the node
        # lifecycle controller ages — starving it evicts healthy nodes
        return BAND_SYSTEM, BAND_SYSTEM
    if seg == "pods" and len(parts) >= 3 and \
            parts[2] in ("bind", "annotations"):
        # bind subresource + allocation stamps: the scheduler's commit
        # path — workload floods must not starve the thing that drains
        # the workload
        return BAND_CONTROLLER, peer or BAND_CONTROLLER
    if seg in _CONTROLLER_SEGMENTS:
        return BAND_CONTROLLER, peer or BAND_CONTROLLER
    tenant = tenant_of_pod(body) if seg == "pods" else None
    return BAND_WORKLOAD, tenant or peer or "anon"


def shuffle_shard(band: str, flow: str, queues: int,
                  hand: int) -> Tuple[int, ...]:
    """The flow's deterministic hand of queue indexes: ``hand`` distinct
    queues dealt from ``queues`` by consuming a SHA-1 of ``(band,
    flow)`` — stable across processes and runs (never Python's seeded
    ``hash``), so tests, replicas, and restarts all agree which queues
    a flow may use."""
    hand = max(1, min(hand, queues))
    value = int.from_bytes(
        hashlib.sha1(f"{band}\x00{flow}".encode()).digest(), "big")
    avail = list(range(queues))
    out: List[int] = []
    for i in range(hand):
        value, pick = divmod(value, queues - i)
        out.append(avail.pop(pick))
    return tuple(out)


class BandConfig:
    """One band's dispatch envelope. ``exempt`` bands bypass queuing
    entirely (the system band); for the rest: ``seats`` bounds
    concurrent execution, ``queues``/``queue_len`` bound waiting work,
    ``hand`` is the shuffle-shard hand size, and ``queue_wait_s`` is
    how long a request may wait for a seat before it is rejected with
    retry-after."""

    def __init__(self, seats: int = 8, queues: int = 16,
                 queue_len: int = 64, queue_wait_s: float = 1.0,
                 hand: int = 4, exempt: bool = False) -> None:
        self.seats = int(seats)
        self.queues = int(queues)
        self.queue_len = int(queue_len)
        self.queue_wait_s = float(queue_wait_s)
        self.hand = int(hand)
        self.exempt = bool(exempt)


def default_bands() -> Dict[str, BandConfig]:
    """The shipped band envelope: system exempt; the controller band
    wide and patient (control loops must converge, not bounce); the
    workload band — the floodable one — tightly bounded."""
    return {
        BAND_SYSTEM: BandConfig(exempt=True),
        BAND_CONTROLLER: BandConfig(seats=16, queues=8, queue_len=256,
                                    queue_wait_s=5.0, hand=4),
        BAND_WORKLOAD: BandConfig(seats=8, queues=16, queue_len=64,
                                  queue_wait_s=1.0, hand=4),
    }


class _Waiter:
    """One queued request. ``admitted`` is flipped by the releasing
    thread (seat handoff) under the band lock."""

    __slots__ = ("admitted",)

    def __init__(self) -> None:
        self.admitted = False


class _Band:
    """Runtime state of one non-exempt band: a monitor (every field
    below is guarded by ``lock``)."""

    def __init__(self, name: str, cfg: BandConfig) -> None:
        self.name = name
        self.cfg = cfg
        self.lock = threading.Condition()
        self.in_use = 0       # seats currently executing
        self.queued = 0       # waiters across all queues
        self.queues: List[deque] = [deque() for _ in range(cfg.queues)]
        self.rr = 0           # round-robin drain cursor


class APFDispatcher:
    """The front door: classify, queue fairly, bound concurrency,
    reject with retry-after. One instance serves both wire framings of
    one apiserver (``serve_api(..., apf=APFDispatcher())``)."""

    def __init__(self,
                 bands: "Dict[str, BandConfig] | None" = None) -> None:
        cfgs = dict(default_bands())
        cfgs.update(bands or {})
        self._configs = cfgs
        self._bands: Dict[str, _Band] = {
            name: _Band(name, cfg) for name, cfg in cfgs.items()
            if not cfg.exempt}

    def band_config(self, band: str) -> BandConfig:
        return self._configs[band]

    def inflight(self, band: str) -> Tuple[int, int]:
        """(executing, queued) for one band — observability + tests."""
        b = self._bands.get(band)
        if b is None:
            return 0, 0
        with b.lock:
            return b.in_use, b.queued

    @contextmanager
    def admit(self, method: str, parts: List[str],
              query: "dict | None" = None, body: object = None,
              peer: str = "") -> Iterator[str]:
        """Gate one request: classify, then hold a seat for the body of
        the ``with``. Raises :class:`TooManyRequests` instead of
        yielding when the band cannot seat the request in time. Exempt
        bands yield immediately — system traffic is never queued."""
        band, flow = classify(method, parts, query, body, peer)
        cfg = self._configs.get(band)
        if cfg is None or cfg.exempt:
            yield band
            return
        wait_s = self._acquire(band, flow)
        metrics.APF_QUEUE_WAIT_MS.observe(wait_s * 1e3)
        try:
            yield band
        finally:
            self._release(band)

    # ---- seat mechanics ----------------------------------------------------

    def _acquire(self, band: str, flow: str) -> float:
        """Take a seat in ``band`` for ``flow``; returns the queue wait
        in seconds. Raises :class:`TooManyRequests` on queue overflow
        or deadline expiry."""
        b = self._bands[band]
        cfg = b.cfg
        with b.lock:
            probe("apf.admit")
            if b.in_use < cfg.seats and b.queued == 0:
                b.in_use += 1
                return 0.0
            hand = shuffle_shard(band, flow, cfg.queues, cfg.hand)
            qi = min(hand, key=lambda i: len(b.queues[i]))
            if len(b.queues[qi]) >= cfg.queue_len:
                # a flow this far behind will not be served by buffering
                # more of it; shed now, with honest advice
                self._reject_locked(b, flow, "queue full")
            waiter = _Waiter()
            b.queues[qi].append(waiter)
            b.queued += 1
            probe("apf.enqueue")
            t0 = time.monotonic()
            deadline = t0 + cfg.queue_wait_s
            while not waiter.admitted:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                b.lock.wait(remaining)
            if waiter.admitted:
                # the releasing thread handed us its seat (in_use was
                # transferred, never decremented)
                return time.monotonic() - t0
            b.queues[qi].remove(waiter)
            b.queued -= 1
            self._reject_locked(b, flow, "queue-wait deadline exceeded")
            raise AssertionError("unreachable")  # _reject_locked raises

    def _reject_locked(self, b: _Band, flow: str, why: str) -> None:
        probe("apf.reject")
        metrics.APF_REJECTS.labels(b.name).inc()
        raise TooManyRequests(
            f"{b.name} band over capacity for flow {flow!r} ({why}: "
            f"{b.in_use}/{b.cfg.seats} seats, {b.queued} queued)",
            retry_after_s=round(b.cfg.queue_wait_s, 3))

    def _release(self, band: str) -> None:
        """Give the seat back — or hand it directly to the next queued
        waiter, drained round-robin ACROSS queues so one deep queue
        (the abusive flow's hand) cannot monopolize freed seats."""
        b = self._bands[band]
        with b.lock:
            probe("apf.release")
            for k in range(len(b.queues)):
                qi = (b.rr + k) % len(b.queues)
                if b.queues[qi]:
                    waiter = b.queues[qi].popleft()
                    b.queued -= 1
                    waiter.admitted = True
                    b.rr = (qi + 1) % len(b.queues)
                    b.lock.notify_all()
                    return
            b.in_use -= 1
            b.lock.notify_all()

"""Kubernetes API client: the same surface as `InMemoryAPIServer` /
`APIClient`, spoken against a **real** Kubernetes API server.

The reference's components talk to the cluster through client-go —
`kubeinterface.PatchNodeMetadata` issues a strategic-merge patch on the
Node (`kubeinterface/kubeinterface.go:145-158`), `UpdatePodMetadata`
updates pod annotations before binding (`:160-193`), and the scheduler
binds via the pods/binding subresource (`kube-scheduler/pkg/
scheduler.go:405-417`). This module is that adapter for the TPU build,
stdlib-only (urllib + ssl): every component (advertiser, scheduler,
runtime hook) takes an ``api`` object, so swapping the in-memory /
HTTP-control-plane server for a real cluster is just constructing
``KubeAPIClient(KubeConfig.load(...))``.

Wire grammar (the real one):

- nodes:      ``/api/v1/nodes[/{name}]``
- pods:       ``/api/v1/namespaces/{ns}/pods[/{name}]``
- bind:       ``POST .../pods/{name}/binding`` with a v1 Binding
- annotations: ``PATCH`` with ``application/strategic-merge-patch+json``
- watches:    ``?watch=true&resourceVersion=N`` chunked JSON-lines

Auth: bearer token or client-cert kubeconfig contexts, plus in-cluster
(serviceaccount token + CA). Tests drive this against a mock API server
speaking the identical grammar (tests/test_kubeclient.py).
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

from kubegpu_tpu import obs
from kubegpu_tpu.cluster.apiserver import Conflict, NotFound

log = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
STRATEGIC_MERGE = "application/strategic-merge-patch+json"


@dataclass
class KubeConfig:
    """Connection settings for one cluster/user pair."""

    server: str
    token: str | None = None
    ca_file: str | None = None
    client_cert: str | None = None
    client_key: str | None = None
    insecure: bool = False
    namespace: str = "default"
    extra_headers: dict = field(default_factory=dict)

    @classmethod
    def load(cls, path: str | None = None, context: str | None = None):
        """Load from a kubeconfig file (``path`` or $KUBECONFIG or
        ~/.kube/config), or fall back to in-cluster settings."""
        path = path or os.environ.get("KUBECONFIG") or \
            os.path.expanduser("~/.kube/config")
        if os.path.exists(path):
            return cls.from_kubeconfig(path, context)
        return cls.in_cluster()

    @classmethod
    def from_kubeconfig(cls, path: str, context: str | None = None):
        import yaml

        with open(path) as f:
            doc = yaml.safe_load(f) or {}

        def by_name(items, name):
            for it in items or []:
                if it.get("name") == name:
                    return it.get(next(k for k in it if k != "name"), {})
            raise ValueError(f"kubeconfig: no entry named {name!r}")

        ctx_name = context or doc.get("current-context")
        ctx = by_name(doc.get("contexts"), ctx_name)
        cluster = by_name(doc.get("clusters"), ctx["cluster"])
        user = by_name(doc.get("users"), ctx["user"]) if ctx.get("user") else {}
        return cls(
            server=cluster["server"].rstrip("/"),
            token=user.get("token"),
            ca_file=cluster.get("certificate-authority"),
            client_cert=user.get("client-certificate"),
            client_key=user.get("client-key"),
            insecure=bool(cluster.get("insecure-skip-tls-verify")),
            namespace=ctx.get("namespace", "default"),
        )

    @classmethod
    def in_cluster(cls):
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError("not running in a cluster (no kubeconfig "
                               "file and KUBERNETES_SERVICE_HOST unset)")
        token = None
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                token = f.read().strip()
        ns = "default"
        ns_path = os.path.join(SA_DIR, "namespace")
        if os.path.exists(ns_path):
            with open(ns_path) as f:
                ns = f.read().strip() or "default"
        ca = os.path.join(SA_DIR, "ca.crt")
        return cls(server=f"https://{host}:{port}", token=token,
                   ca_file=ca if os.path.exists(ca) else None, namespace=ns)


class KubeAPIClient:
    """`InMemoryAPIServer`-shaped facade over the real Kubernetes REST API.

    ``add_watcher`` starts informer threads (one per resource kind) that
    stream ``?watch=true`` events and replay them as the in-process
    ``(kind, event, obj)`` callbacks the scheduler/advertiser expect.
    """

    def __init__(self, config: KubeConfig, timeout: float = 30.0):
        self.config = config
        self.timeout = timeout
        self.namespace = config.namespace
        self._watchers: list = []
        self._watch_threads: list = []
        self._stop = threading.Event()
        self._ssl = self._make_ssl_context()

    def _make_ssl_context(self):
        if not self.config.server.startswith("https"):
            return None
        if self.config.insecure:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        else:
            ctx = ssl.create_default_context(cafile=self.config.ca_file)
        if self.config.client_cert:
            ctx.load_cert_chain(self.config.client_cert,
                                self.config.client_key)
        return ctx

    # -- plumbing -----------------------------------------------------------

    def _headers(self, content_type: str = "application/json") -> dict:
        h = {"Content-Type": content_type, "Accept": "application/json"}
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        trace_ctx = obs.header_value()
        if trace_ctx is not None:
            # the binder's span context rides every write it performs
            # (annotate/bind), same contract as the HTTP control-plane
            # client — a tracing sidecar/proxy can continue the trace
            h[obs.TRACE_HEADER] = trace_ctx
        h.update(self.config.extra_headers)
        return h

    def _req(self, method: str, path: str, body=None,
             content_type: str = "application/json", timeout=None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.config.server + path, data=data, method=method,
            headers=self._headers(content_type))
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout,
                    context=self._ssl) as resp:
                return json.loads(resp.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            payload = e.read().decode()
            if e.code == 404:
                raise NotFound(payload)
            if e.code == 409:
                raise Conflict(payload)
            raise RuntimeError(f"{method} {path} -> HTTP {e.code}: {payload}")

    def _pod_path(self, name: str = "", sub: str = "") -> str:
        base = f"/api/v1/namespaces/{self.namespace}/pods"
        if name:
            base += f"/{urllib.parse.quote(name)}"
        if sub:
            base += f"/{sub}"
        return base

    # -- nodes --------------------------------------------------------------

    def create_node(self, node: dict) -> dict:
        return self._req("POST", "/api/v1/nodes", node)

    def get_node(self, name: str) -> dict:
        return self._req("GET", f"/api/v1/nodes/{urllib.parse.quote(name)}")

    def list_nodes(self) -> list:
        return self._req("GET", "/api/v1/nodes").get("items") or []

    def patch_node_metadata(self, name: str, metadata_patch: dict) -> dict:
        """Strategic-merge patch of node metadata — the advertiser's write
        path (`kubeinterface.go:145-158`)."""
        return self._req(
            "PATCH", f"/api/v1/nodes/{urllib.parse.quote(name)}",
            {"metadata": metadata_patch}, content_type=STRATEGIC_MERGE)

    def delete_node(self, name: str) -> None:
        self._req("DELETE", f"/api/v1/nodes/{urllib.parse.quote(name)}")

    # -- pods ---------------------------------------------------------------

    def create_pod(self, pod: dict) -> dict:
        return self._req("POST", self._pod_path(), pod)

    def get_pod(self, name: str) -> dict:
        return self._req("GET", self._pod_path(name))

    def list_pods(self, node_name: str | None = None,
                  phase: str | None = None, bound: bool = False) -> list:
        path = self._pod_path()
        selectors = []
        if node_name:
            selectors.append(f"spec.nodeName={node_name}")
        if phase:
            selectors.append(f"status.phase={phase}")
        if selectors:
            sel = urllib.parse.quote(",".join(selectors))
            path += f"?fieldSelector={sel}"
        items = self._req("GET", path).get("items") or []
        if bound:
            # the real apiserver has no "nodeName is set" field selector;
            # filtering client-side keeps the surface identical to the
            # in-memory/HTTP servers' bound index
            items = [p for p in items
                     if (p.get("spec") or {}).get("nodeName")]
        return items

    def update_pod_annotations(self, name: str, annotations: dict) -> dict:
        """Annotation-only strategic-merge patch — `UpdatePodMetadata`'s
        contract (`kubeinterface.go:175-193`): never touches spec/status."""
        return self._req(
            "PATCH", self._pod_path(name),
            {"metadata": {"annotations": annotations}},
            content_type=STRATEGIC_MERGE)

    def update_pod_annotations_many(self, annotations: dict) -> None:
        """Batched annotation replace. Kubernetes has no multi-object
        patch, so this degrades to one PATCH per pod — callers written
        against the batched surface stay correct on a real cluster and
        get the single-request form on the in-memory/HTTP servers. Every
        pod is attempted (one deleted pod must not strand its
        batch-mates' stamps) and missing pods are reported per-pod, the
        same NotFound shape the in-memory server raises."""
        missing: dict = {}
        conflicts: dict = {}
        other: list = []
        for name, ann in sorted(annotations.items()):
            try:
                self.update_pod_annotations(name, ann)
            except NotFound:
                missing[name] = "not found"
            except Conflict as e:
                # a 409 is the server's definitive refusal — it must
                # stay a typed Conflict with per-pod detail, or callers
                # would retry-in-place a refusal the server repeats
                conflicts[name] = str(e)
            except Exception as e:  # noqa: BLE001
                other.append((name, e))
        if other:
            name, err = other[0]
            raise RuntimeError(
                f"annotation batch failed for {[n for n, _ in other]}; "
                f"first: {name}: {err}") from err
        if conflicts:
            raise Conflict(
                f"annotation batch refused for {sorted(conflicts)}",
                per_pod=conflicts)
        if missing:
            raise NotFound(f"pods not found: {sorted(missing)}",
                           per_pod=missing)

    def bind_pod(self, name: str, node_name: str) -> None:
        """POST the v1 Binding subresource (`scheduler.go:405-417`)."""
        self._req("POST", self._pod_path(name, "binding"), {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": name, "namespace": self.namespace},
            "target": {"apiVersion": "v1", "kind": "Node",
                       "name": node_name},
        })

    def bind_many(self, bindings: dict, annotations: dict) -> None:
        """Gang commit against a real API server. Kubernetes has no
        atomic multi-bind; this is annotate-everything-then-
        bind-everything, every member attempted (the in-memory server's
        bind_many is the atomic analogue used for single-process runs).
        Failures are reported PER POD: all-Conflict failures raise a
        ``Conflict`` with ``per_pod`` detail — the same shape the
        arbiter raises, so the binder's taken-chip handling (forget +
        requeue the losers, never blind-retry) works against a real
        cluster too — and anything else raises with the already-bound
        members listed so the caller can reconcile. The annotate stage
        shares `update_pod_annotations_many`'s every-member-attempted /
        per-pod-errors contract."""
        self.update_pod_annotations_many(annotations)
        bound: list = []
        conflicts: dict = {}
        other: list = []
        for name, node_name in sorted(bindings.items()):
            try:
                self.bind_pod(name, node_name)
                bound.append(name)
            except Conflict as e:
                conflicts[name] = str(e)
            except Exception as e:  # noqa: BLE001
                other.append((name, e))
        if other:
            name, err = other[0]
            raise RuntimeError(
                f"gang bind partially failed (bound {bound}, failed "
                f"{[n for n, _ in other]}): {err}") from err
        if conflicts:
            raise Conflict(
                f"bind refused for {len(conflicts)} pod(s) "
                f"(bound {bound})", per_pod=conflicts)

    def delete_pod(self, name: str) -> None:
        self._req("DELETE", self._pod_path(name))

    # -- persistent volumes / claims ----------------------------------------
    # PVCs are namespaced, PVs cluster-scoped (the real wire grammar). The
    # scheduler's volume binder consumes exactly this surface
    # (`volumebinder/volume_binder.go:1-74`).

    # -- selector owners (SelectorSpreadPriority listers) --------------------
    # The four owner kinds `selector_spreading.go`'s getSelectors lists.
    # List-only: this scheduler never creates them on a real cluster.

    def list_services(self) -> list:
        return self._req(
            "GET", f"/api/v1/namespaces/{self.namespace}/services"
        ).get("items") or []

    def list_rcs(self) -> list:
        return self._req(
            "GET",
            f"/api/v1/namespaces/{self.namespace}/replicationcontrollers"
        ).get("items") or []

    def list_rss(self) -> list:
        return self._req(
            "GET",
            f"/apis/apps/v1/namespaces/{self.namespace}/replicasets"
        ).get("items") or []

    def list_statefulsets(self) -> list:
        return self._req(
            "GET",
            f"/apis/apps/v1/namespaces/{self.namespace}/statefulsets"
        ).get("items") or []

    def _pvc_path(self, name: str = "") -> str:
        base = f"/api/v1/namespaces/{self.namespace}/persistentvolumeclaims"
        return base + (f"/{urllib.parse.quote(name)}" if name else "")

    @staticmethod
    def _pv_path(name: str = "") -> str:
        base = "/api/v1/persistentvolumes"
        return base + (f"/{urllib.parse.quote(name)}" if name else "")

    def create_pvc(self, pvc: dict) -> dict:
        return self._req("POST", self._pvc_path(), pvc)

    def get_pvc(self, name: str) -> dict:
        return self._req("GET", self._pvc_path(name))

    def list_pvcs(self) -> list:
        return self._req("GET", self._pvc_path()).get("items") or []

    def delete_pvc(self, name: str) -> None:
        self._req("DELETE", self._pvc_path(name))

    def create_pv(self, pv: dict) -> dict:
        return self._req("POST", self._pv_path(), pv)

    def get_pv(self, name: str) -> dict:
        return self._req("GET", self._pv_path(name))

    def list_pvs(self) -> list:
        return self._req("GET", self._pv_path()).get("items") or []

    def delete_pv(self, name: str) -> None:
        self._req("DELETE", self._pv_path(name))

    def bind_volume(self, pv_name: str, claim_name: str) -> None:
        """Commit a claim<->volume pairing the way the real binder does:
        patch the PV's ``claimRef``, then the PVC's ``volumeName`` (two
        strategic-merge patches — Kubernetes has no atomic pair-bind; the
        PV patch first makes the reservation visible before the claim
        flips).

        Re-claim guard: a real apiserver merges a claimRef patch over an
        existing one without complaint, so each side is GET-verified
        first (Conflict on a foreign pairing) and the observed
        ``resourceVersion`` rides in the patch body, which makes the
        write an optimistic test-and-set on servers that stamp it — an
        external binder racing into the GET->PATCH window loses to the
        precondition instead of being silently overwritten."""
        pv = self.get_pv(pv_name)
        ref = (pv.get("spec") or {}).get("claimRef")
        if ref and (ref.get("name") != claim_name
                    or (ref.get("namespace") or self.namespace)
                    != self.namespace):
            raise Conflict(
                f"pv {pv_name} already claimed by "
                f"{ref.get('namespace') or self.namespace}/{ref.get('name')}")
        body: dict = {"spec": {"claimRef": {"name": claim_name,
                                            "namespace": self.namespace}}}
        rv = (pv.get("metadata") or {}).get("resourceVersion")
        if rv:
            body["metadata"] = {"resourceVersion": rv}
        self._req("PATCH", self._pv_path(pv_name), body,
                  content_type=STRATEGIC_MERGE)
        pvc = self.get_pvc(claim_name)
        bound = (pvc.get("spec") or {}).get("volumeName")
        if bound and bound != pv_name:
            raise Conflict(f"pvc {claim_name} already bound to {bound}")
        body = {"spec": {"volumeName": pv_name}}
        rv = (pvc.get("metadata") or {}).get("resourceVersion")
        if rv:
            body["metadata"] = {"resourceVersion": rv}
        self._req("PATCH", self._pvc_path(claim_name), body,
                  content_type=STRATEGIC_MERGE)

    # -- watches ------------------------------------------------------------

    def add_watcher(self, fn) -> None:
        """Register ``fn(kind, event, obj)``; the first registration spawns
        watch threads for nodes and pods."""
        self._watchers.append(fn)
        if not self._watch_threads:
            for kind, path in (
                    ("node", "/api/v1/nodes"),
                    ("pod", self._pod_path()),
                    ("pvc", self._pvc_path()),
                    ("pv", "/api/v1/persistentvolumes")):
                t = threading.Thread(
                    target=self._watch_loop, args=(kind, path), daemon=True,
                    name=f"kubewatch-{kind}")
                t.start()
                self._watch_threads.append(t)

    def _watch_loop(self, kind: str, path: str) -> None:
        version = ""
        while not self._stop.is_set():
            try:
                # (Re)list to get a resourceVersion, then stream from it.
                if not version:
                    listing = self._req("GET", path)
                    version = (listing.get("metadata") or {}).get(
                        "resourceVersion") or "0"
                    for obj in listing.get("items") or []:
                        self._dispatch(kind, "added", obj)
                q = urllib.parse.urlencode(
                    {"watch": "true", "resourceVersion": version})
                req = urllib.request.Request(
                    f"{self.config.server}{path}?{q}",
                    headers=self._headers())
                with urllib.request.urlopen(
                        req, timeout=None, context=self._ssl) as resp:
                    for line in resp:
                        if self._stop.is_set():
                            return
                        line = line.strip()
                        if not line:
                            continue
                        evt = json.loads(line.decode())
                        obj = evt.get("object") or {}
                        version = (obj.get("metadata") or {}).get(
                            "resourceVersion") or version
                        self._dispatch(
                            kind, evt.get("type", "").lower(), obj)
            except Exception:
                if self._stop.is_set():
                    return
                version = ""  # relist after a dropped watch
                self._stop.wait(1.0)

    def _dispatch(self, kind: str, event: str, obj: dict) -> None:
        if event not in ("added", "modified", "deleted"):
            return  # BOOKMARK / ERROR frames
        for fn in list(self._watchers):
            try:
                fn(kind, event, obj)
            except Exception:
                # a bad watcher must not kill the informer, but it must
                # not fail invisibly either
                log.warning("watch consumer %r failed on %s %s event",
                            fn, kind, event, exc_info=True)

    def close(self) -> None:
        self._stop.set()

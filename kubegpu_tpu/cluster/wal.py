"""Durable write-ahead log + snapshot/compaction for the API server.

The in-memory API server is fast but volatile: a restart used to lose
every object AND the watch event log, stranding every informer at a
sequence number the new process had never issued. This module makes the
event stream the unit of durability — every watch event ``(seq, kind,
event, obj)`` is one WAL record, so replaying the log rebuilds both the
object state (the events carry whole objects) and the exact watch-resume
cursor space.

Format (little-endian, one record per event):

    [4-byte payload length][4-byte CRC32 of payload][payload]
    payload = JSON [seq, kind, event, obj]

A torn tail — the process died mid-append — is detected by the length or
checksum and DROPPED, never fatal: the lost suffix was never
acknowledged to any client that matters (watch delivery happens after
the append returns).

Snapshot + compaction: every ``snapshot_every`` appends the server's
full object state is written to ``snapshot.json`` (tmp + fsync +
atomic rename) and the log truncated. Recovery loads the snapshot, then
replays any WAL records with a later sequence number; a crash between
snapshot and truncate is safe because replay skips records at or below
the snapshot's sequence. Clients that present a pre-snapshot ``since``
cannot be replayed exactly — the serving layer answers them with a
full-relist signal instead of a silent gap.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import zlib
from typing import Any, BinaryIO, List, Optional, Tuple

from kubegpu_tpu import metrics
from kubegpu_tpu.analysis.explore import probe

log = logging.getLogger(__name__)

_HEADER = struct.Struct("<II")  # payload length, CRC32(payload)

WAL_FILE = "wal.log"
SNAPSHOT_FILE = "snapshot.json"

# One WAL event record, exactly the watch-log tuple shape.
Record = Tuple[int, str, str, Any]


class WriteAheadLog:
    """Length-prefixed, checksummed WAL with periodic snapshot+compaction.

    ``fsync=False`` trades durability-to-media for speed (still durable
    across process crashes — the OS holds the page cache); benches and
    chaos scenarios use it, real deployments keep the default.
    """

    def __init__(self, dir_path: str, fsync: bool = True,
                 snapshot_every: int = 4096) -> None:
        self.dir_path = dir_path
        self.fsync = fsync
        self.snapshot_every = max(1, snapshot_every)
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.Lock()
        self._fh: Optional[BinaryIO] = None
        self._closed = False
        self._since_snapshot = 0
        self.appended_total = 0
        self.recovered_records = 0
        self.dropped_tail_bytes = 0

    @property
    def wal_path(self) -> str:
        return os.path.join(self.dir_path, WAL_FILE)

    def stream_epoch(self) -> str:
        """Stable identity of this WAL's event stream, minted once per
        directory and persisted: a watch client uses it to tell "same
        stream, sequence continues" (WAL-backed restart) from "new
        stream that happens to have overlapping sequence numbers" (a
        different/wiped store) — the case a bare seq comparison cannot
        catch."""
        path = os.path.join(self.dir_path, "epoch")
        try:
            with open(path) as fh:
                return fh.read().strip()
        except FileNotFoundError:
            token = os.urandom(8).hex()
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(token)
            os.replace(tmp, path)
            return token

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.dir_path, SNAPSHOT_FILE)

    # ---- append ------------------------------------------------------------

    @staticmethod
    def _encode(seq: int, kind: str, event: str, obj: Any) -> bytes:
        payload = json.dumps([seq, kind, event, obj],
                             separators=(",", ":"), default=str).encode()
        return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload

    def append(self, seq: int, kind: str, event: str, obj: Any) -> None:
        """Append one event record and make it durable (write + flush,
        plus fsync when enabled). Called by the event log BEFORE the
        event is served to any watcher — write-ahead, so anything a
        client saw is replayable."""
        probe("wal.append")
        data = self._encode(seq, kind, event, obj)
        t0 = time.perf_counter()
        with self._lock:
            fh = self._open_locked()
            fh.write(data)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
            self.appended_total += 1
            self._since_snapshot += 1
        metrics.WAL_FSYNC_MS.observe((time.perf_counter() - t0) * 1e3)

    def _open_locked(self) -> BinaryIO:
        # Always called with self._lock held.
        if self._closed:
            # close() latches: an in-flight mutator racing a server
            # shutdown must not quietly reopen the handle the shutdown
            # just released — its request fails instead (the client's
            # retry/reconnect layer owns what happens next)
            raise RuntimeError("write-ahead log is closed")
        if self._fh is None:
            self._fh = open(self.wal_path, "ab")
        return self._fh

    def due_for_snapshot(self) -> bool:
        with self._lock:
            return self._since_snapshot >= self.snapshot_every

    # ---- snapshot + compaction ---------------------------------------------

    def snapshot(self, state: Any, seq: int,
                 tail: Any = None) -> None:
        """Persist the full object state at ``seq`` and truncate the log.
        Ordering is what makes a crash at any point recoverable: the
        snapshot lands durably (tmp + fsync + atomic rename) BEFORE the
        WAL truncates, and recovery skips WAL records at or below the
        snapshot's sequence — so a crash between the two steps replays
        nothing twice and loses nothing. ``tail`` (recent event records
        already reflected in ``state``) rides along so the watch-resume
        window extends BELOW the compaction point: a client a few events
        behind the final pre-crash snapshot still resumes seq-exact
        instead of relisting."""
        doc = json.dumps({"seq": seq, "state": state,
                          "tail": list(tail or [])},
                         default=str).encode()
        tmp = self.snapshot_path + ".tmp"
        with self._lock:
            if self._closed:
                raise RuntimeError("write-ahead log is closed")
            with open(tmp, "wb") as fh:
                fh.write(doc)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.snapshot_path)
            if self._fh is not None:
                self._fh.close()
            self._fh = open(self.wal_path, "wb")  # truncate
            self._since_snapshot = 0
        metrics.WAL_SNAPSHOT_BYTES.set(len(doc))
        log.info("wal snapshot at seq %d (%d bytes); log compacted",
                 seq, len(doc))

    # ---- recovery ----------------------------------------------------------

    def load_snapshot(self) -> Tuple[int, Any, List[Record]]:
        """``(seq, state, tail)`` from the snapshot file, or
        ``(0, None, [])``."""
        try:
            with open(self.snapshot_path, "rb") as fh:
                doc = json.loads(fh.read().decode())
            tail = [(int(s), k, e, o)
                    for s, k, e, o in (doc.get("tail") or [])]
            return int(doc.get("seq", 0)), doc.get("state"), tail
        except FileNotFoundError:
            return 0, None, []
        except (ValueError, OSError):
            # a torn snapshot write never replaces the previous snapshot
            # (atomic rename), so a corrupt file here is pre-atomic-rename
            # debris or external damage: recover from the WAL alone
            log.warning("unreadable snapshot %s; recovering from the WAL "
                        "alone", self.snapshot_path, exc_info=True)
            return 0, None, []

    def read_records(self, after_seq: int = 0) -> List[Record]:
        """Decode WAL records with seq > ``after_seq``, truncating any
        torn tail in place (mid-append crash: the partial record was
        never acknowledged, dropping it is the correct recovery)."""
        records: List[Record] = []
        try:
            fh = open(self.wal_path, "rb")
        except FileNotFoundError:
            return records
        with fh:
            good_end = 0
            while True:
                header = fh.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, crc = _HEADER.unpack(header)
                payload = fh.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                try:
                    seq, kind, event, obj = json.loads(payload.decode())
                except ValueError:
                    break
                good_end = fh.tell()
                if seq > after_seq:
                    records.append((int(seq), kind, event, obj))
            end = fh.seek(0, os.SEEK_END)
            torn = end - good_end
        if torn > 0:
            self.dropped_tail_bytes += torn
            log.warning("wal: dropping %d torn tail byte(s) at offset %d",
                        torn, good_end)
            with open(self.wal_path, "r+b") as trunc:
                trunc.truncate(good_end)
        return records

    def recover(self, api: Any) -> Tuple[int, int, List[Record]]:
        """Rebuild ``api``'s state: snapshot first, then WAL replay.
        Returns ``(last_seq, floor, resume_records)`` for the event log:
        ``floor`` is the oldest sequence number still replayable
        (snapshot seq, lowered by the snapshot's retained event tail) —
        clients presenting an older ``since`` get a relist signal,
        everyone else resumes seq-exact from ``resume_records``. Tail
        records are already reflected in the snapshot state and are NOT
        re-applied — they only serve resume."""
        snap_seq, state, tail = self.load_snapshot()
        if state is not None:
            api.restore_state(state)
        floor = snap_seq
        if tail:
            floor = min(floor, tail[0][0] - 1)
        last_seq = snap_seq
        records = self.read_records(after_seq=snap_seq)
        for seq, kind, event, obj in records:
            try:
                api.restore_object(kind, event, obj)
            except Exception:
                # one unreplayable record must not void the rest of the
                # recovery — the object state it carried is skipped, the
                # sequence space stays intact
                log.warning("wal replay: could not restore %s %s record "
                            "seq %d", kind, event, seq, exc_info=True)
            last_seq = max(last_seq, seq)
        self.recovered_records = len(records)
        if records or state is not None:
            log.info("wal recovery: snapshot seq %d (+%d tail) + %d "
                     "replayed record(s) -> seq %d", snap_seq, len(tail),
                     len(records), last_seq)
        return last_seq, floor, tail + records

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None

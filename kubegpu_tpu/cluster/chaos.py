"""Chaos transport: a seeded, deterministic fault-injection proxy over the
API-client surface.

Every component in this framework (scheduler, node agent, lifecycle
controller, runtime hook) talks to the control plane through one client
surface — ``InMemoryAPIServer`` in-process or ``HTTPAPIClient`` over the
wire (`cluster/httpapi.py` implements the identical methods). That makes
the transport the single choke point where network failure can be
injected for ALL of them: a ``ChaosProxy`` wraps any such client and,
per call, may

- **drop** the request (raise ``ConnectionError`` before it is sent —
  the caller sees a transient transport failure, the server never does),
- **delay** it (sleep before delivery),
- **duplicate** it (deliver twice; the second delivery's outcome is
  discarded — the at-least-once retry a real network can produce), or
- **partition** the component (every call fails until ``heal``).

Faults draw from one seeded RNG owned by the shared ``ChaosNetwork``, so
a single-threaded driver replays the identical fault sequence for a
given seed — the property the chaos tests assert three runs in a row.

Verbs can be scoped (``verbs=`` / ``exempt=``) so a test can target the
write path while leaving reads clean. ``add_watcher``/``close`` are
always passed through un-faulted: watch registration is process wiring,
not a request.
"""

from __future__ import annotations

import random
import threading
import time

# Verbs never faulted: local wiring, not requests on the wire.
_PASSTHROUGH = {"add_watcher", "close"}


class ChaosConfig:
    """Per-component fault rates. ``drop``/``delay``/``duplicate`` are
    probabilities per call; ``delay_s`` the injected latency."""

    def __init__(self, drop: float = 0.0, delay: float = 0.0,
                 delay_s: float = 0.002, duplicate: float = 0.0,
                 verbs: set | frozenset | None = None,
                 exempt: set | frozenset | None = None):
        self.drop = drop
        self.delay = delay
        self.delay_s = delay_s
        self.duplicate = duplicate
        self.verbs = frozenset(verbs) if verbs is not None else None
        self.exempt = frozenset(exempt or ())

    def applies_to(self, verb: str) -> bool:
        if verb in self.exempt:
            return False
        return self.verbs is None or verb in self.verbs


class ChaosNetwork:
    """Shared fault source for a set of proxied components: one seeded
    RNG (deterministic replay), per-component configs, and the partition
    set."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._partitioned: set = set()
        self.faults: dict = {}  # (component, kind) -> count

    def proxy(self, api, component: str,
              config: ChaosConfig | None = None) -> "ChaosProxy":
        return ChaosProxy(self, api, component, config or ChaosConfig())

    # ---- partitions --------------------------------------------------------

    def partition(self, *components: str) -> None:
        """Cut the named components off from the API server entirely."""
        with self._lock:
            self._partitioned.update(components)

    def heal(self, *components: str) -> None:
        """Reconnect components (no args = heal everything)."""
        with self._lock:
            if components:
                self._partitioned.difference_update(components)
            else:
                self._partitioned.clear()

    def is_partitioned(self, component: str) -> bool:
        with self._lock:
            return component in self._partitioned

    # ---- fault drawing -----------------------------------------------------

    def _count(self, component: str, kind: str) -> None:
        key = (component, kind)
        self.faults[key] = self.faults.get(key, 0) + 1

    def draw(self, component: str, verb: str, config: ChaosConfig):
        """Decide this call's fate. Returns (delay_s, duplicate) or
        raises ConnectionError for drops/partitions. One lock-guarded
        RNG draw sequence per call keeps a given seed's fault schedule
        reproducible under a single-threaded driver."""
        with self._lock:
            if component in self._partitioned:
                self._count(component, "partition")
                raise ConnectionError(
                    f"chaos: {component} is partitioned from the API "
                    f"server ({verb})")
            if not config.applies_to(verb):
                return 0.0, False
            roll = self._rng.random()
            delay_s = 0.0
            duplicate = False
            if roll < config.drop:
                self._count(component, "drop")
                raise ConnectionError(
                    f"chaos: dropped {component}.{verb}")
            roll = self._rng.random()
            if roll < config.delay:
                self._count(component, "delay")
                delay_s = config.delay_s
            roll = self._rng.random()
            if roll < config.duplicate:
                self._count(component, "duplicate")
                duplicate = True
            return delay_s, duplicate


class ChaosProxy:
    """Duck-typed stand-in for the API client it wraps: every callable
    attribute goes through the chaos network first."""

    def __init__(self, net: ChaosNetwork, api, component: str,
                 config: ChaosConfig):
        self._net = net
        self._api = api
        self._component = component
        self._config = config

    def __getattr__(self, name: str):
        real = getattr(self._api, name)
        if not callable(real) or name.startswith("_") \
                or name in _PASSTHROUGH:
            return real

        def wrapper(*args, **kwargs):
            delay_s, duplicate = self._net.draw(
                self._component, name, self._config)
            if delay_s > 0:
                time.sleep(delay_s)
            result = real(*args, **kwargs)
            if duplicate:
                # at-least-once delivery: the duplicate's outcome (often
                # a Conflict on create, a no-op on idempotent verbs) is
                # the network's problem, not the caller's
                try:
                    real(*args, **kwargs)
                except Exception:
                    pass
            return result
        return wrapper

"""Chaos transport: a seeded, deterministic fault-injection proxy over the
API-client surface.

Every component in this framework (scheduler, node agent, lifecycle
controller, runtime hook) talks to the control plane through one client
surface — ``InMemoryAPIServer`` in-process or ``HTTPAPIClient`` over the
wire (`cluster/httpapi.py` implements the identical methods). That makes
the transport the single choke point where network failure can be
injected for ALL of them: a ``ChaosProxy`` wraps any such client and,
per call, may

- **drop** the request (raise ``ConnectionError`` before it is sent —
  the caller sees a transient transport failure, the server never does),
- **delay** it (sleep before delivery),
- **duplicate** it (deliver twice; the second delivery's outcome is
  discarded — the at-least-once retry a real network can produce), or
- **partition** the component (every call fails until ``heal``).

Faults draw from one seeded RNG owned by the shared ``ChaosNetwork``, so
a single-threaded driver replays the identical fault sequence for a
given seed — the property the chaos tests assert three runs in a row.

Verbs can be scoped (``verbs=`` / ``exempt=``) so a test can target the
write path while leaving reads clean. ``add_watcher``/``close`` are
always passed through un-faulted: watch registration is process wiring,
not a request.
"""

from __future__ import annotations

import itertools
import random
import threading
import time

from kubegpu_tpu.core import codec, grammar
from kubegpu_tpu.core.types import ContainerInfo, PodInfo

# Verbs never faulted: local wiring, not requests on the wire.
_PASSTHROUGH = {"add_watcher", "close"}


class ChaosConfig:
    """Per-component fault rates. ``drop``/``delay``/``duplicate`` are
    probabilities per call; ``delay_s`` the injected latency."""

    def __init__(self, drop: float = 0.0, delay: float = 0.0,
                 delay_s: float = 0.002, duplicate: float = 0.0,
                 verbs: set | frozenset | None = None,
                 exempt: set | frozenset | None = None):
        self.drop = drop
        self.delay = delay
        self.delay_s = delay_s
        self.duplicate = duplicate
        self.verbs = frozenset(verbs) if verbs is not None else None
        self.exempt = frozenset(exempt or ())

    def applies_to(self, verb: str) -> bool:
        if verb in self.exempt:
            return False
        return self.verbs is None or verb in self.verbs


class ChaosNetwork:
    """Shared fault source for a set of proxied components: one seeded
    RNG (deterministic replay), per-component configs, and the partition
    set."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._partitioned: set = set()
        self.faults: dict = {}  # (component, kind) -> count

    def proxy(self, api, component: str,
              config: ChaosConfig | None = None) -> "ChaosProxy":
        return ChaosProxy(self, api, component, config or ChaosConfig())

    # ---- partitions --------------------------------------------------------

    def partition(self, *components: str) -> None:
        """Cut the named components off from the API server entirely."""
        with self._lock:
            self._partitioned.update(components)

    def heal(self, *components: str) -> None:
        """Reconnect components (no args = heal everything)."""
        with self._lock:
            if components:
                self._partitioned.difference_update(components)
            else:
                self._partitioned.clear()

    def is_partitioned(self, component: str) -> bool:
        with self._lock:
            return component in self._partitioned

    # ---- fault drawing -----------------------------------------------------

    def _count(self, component: str, kind: str) -> None:
        key = (component, kind)
        self.faults[key] = self.faults.get(key, 0) + 1

    def draw(self, component: str, verb: str, config: ChaosConfig):
        """Decide this call's fate. Returns (delay_s, duplicate) or
        raises ConnectionError for drops/partitions. One lock-guarded
        RNG draw sequence per call keeps a given seed's fault schedule
        reproducible under a single-threaded driver."""
        with self._lock:
            if component in self._partitioned:
                self._count(component, "partition")
                raise ConnectionError(
                    f"chaos: {component} is partitioned from the API "
                    f"server ({verb})")
            if not config.applies_to(verb):
                return 0.0, False
            roll = self._rng.random()
            delay_s = 0.0
            duplicate = False
            if roll < config.drop:
                self._count(component, "drop")
                raise ConnectionError(
                    f"chaos: dropped {component}.{verb}")
            roll = self._rng.random()
            if roll < config.delay:
                self._count(component, "delay")
                delay_s = config.delay_s
            roll = self._rng.random()
            if roll < config.duplicate:
                self._count(component, "duplicate")
                duplicate = True
            return delay_s, duplicate


class TenantFlood:
    """The abusive-tenant fault: N threads hammer pod creates for ONE
    tenant as fast as the transport answers, deliberately ignoring the
    server's advised retry-after (a well-behaved client would defer; an
    abuser by definition does not). The driver behind the
    ``tenant-flood`` chaos scenario (`cmd/simulate.py`): start it
    against a front-doored apiserver, churn well-behaved tenants
    alongside, and the priority-&-fairness layer plus the DRF chip
    gate must hold their p99 while this runs.

    ``pace_s`` models the floor a real network puts under even an
    abusive client (one RTT per request); 0 is an infinitely fast
    attacker. Counts are returned by :meth:`stop`:
    ``accepted``/``rejected`` (typed 429s)/``errored``.

    ``mode="read"`` floods list/get traffic instead of pod creates —
    the watch-cache-proxy scenario's abuser: reads are exactly what a
    proxy replica absorbs from its mirror, so a read flood at the proxy
    tier must leave the apiserver's request rate flat while a create
    flood would still forward upstream (bounded by the replica's own
    front door).
    """

    def __init__(self, client_factory, tenant: str = "abuser",
                 threads: int = 4, chips: int = 1,
                 pace_s: float = 0.001, mode: str = "mutate"):
        if mode not in ("mutate", "read"):
            raise ValueError(f"unknown flood mode {mode!r}")
        self._factory = client_factory
        self.tenant = tenant
        self.threads = threads
        self.chips = chips
        self.pace_s = pace_s
        self.mode = mode
        self._stop = threading.Event()
        # racer: single-writer -- start()/stop() are the driver
        # thread's lifecycle calls; flood workers never touch these
        self._workers: list = []
        # racer: single-writer -- same owner-thread lifecycle contract
        self._clients: list = []
        self._lock = threading.Lock()
        self.accepted = 0
        self.rejected = 0
        self.errored = 0
        self._seq = itertools.count()

    def _flood_pod(self, name: str) -> dict:
        pi = PodInfo(name=name)
        pi.running_containers["main"] = ContainerInfo(
            requests={grammar.RESOURCE_NUM_CHIPS: self.chips})
        meta = {"name": name,
                "labels": {"kgtpu.io/tenant": self.tenant}}
        codec.pod_info_to_annotation(meta, pi)
        return {"metadata": meta,
                "spec": {"containers": [
                    {"name": "main",
                     "resources": {"requests": {"cpu": "1"}}}]}}

    def _run(self, client) -> None:
        from kubegpu_tpu.cluster.apf import TooManyRequests

        while not self._stop.is_set():
            name = f"{self.tenant}-flood-{next(self._seq)}"
            try:
                if self.mode == "read":
                    client.list_pods()
                else:
                    client.create_pod(self._flood_pod(name))
                with self._lock:
                    self.accepted += 1
            except TooManyRequests:
                # the front door shed us; an abuser retries immediately
                with self._lock:
                    self.rejected += 1
            except Exception:
                with self._lock:
                    self.errored += 1
            if self.pace_s > 0:
                self._stop.wait(self.pace_s)

    def start(self) -> "TenantFlood":
        for _ in range(self.threads):
            client = self._factory()
            self._clients.append(client)
            worker = threading.Thread(target=self._run, args=(client,),
                                      daemon=True, name="tenant-flood")
            self._workers.append(worker)
            worker.start()
        return self

    def stop(self) -> dict:
        """Stop the flood, join the workers, close their clients, and
        return the accounting."""
        self._stop.set()
        for worker in self._workers:
            worker.join(timeout=10.0)
        self._workers = []
        for client in self._clients:
            close = getattr(client, "close", None)
            if close is not None:
                close()
        self._clients = []
        with self._lock:
            return {"accepted": self.accepted,
                    "rejected": self.rejected,
                    "errored": self.errored}


class DeviceChaos:
    """Seeded device-level fault injector: chip-kill, chip-flap, and
    ICI-link-down against a set of ``FakeTPUBackend``s.

    Where :class:`ChaosNetwork` breaks the *transport*, this breaks the
    *hardware* under it — the advertiser then reports the damage through
    the ordinary health/link annotations and the repair controller takes
    it from there. All choice (which node, which chip, which link
    direction) comes from one seeded RNG, so a schedule of N faults is a
    pure function of the seed; :meth:`plan` materializes that schedule
    up front for soak tests that want to log and replay it.
    """

    KINDS = ("chip-kill", "chip-flap", "link-down")

    def __init__(self, backends: dict, seed: int = 0):
        # {node_name: FakeTPUBackend}; iteration order is sorted so the
        # draw sequence is independent of dict construction order
        self._backends = dict(backends)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected: list = []  # (kind, node, chip_id, detail) in order

    def _pick(self, node: str | None, chip_id: str | None):
        """Resolve (node, backend, chip) — seeded draw for whatever the
        caller left unspecified."""
        node = node if node is not None \
            else self._rng.choice(sorted(self._backends))
        backend = self._backends[node]
        chips = backend.inventory.chips
        if chip_id is None:
            chip = chips[self._rng.randrange(len(chips))]
        else:
            chip = backend.inventory.chip(chip_id)
            if chip is None:
                raise KeyError(f"chip {chip_id} not on node {node}")
        return node, backend, chip

    def kill_chip(self, node: str | None = None,
                  chip_id: str | None = None) -> tuple:
        """Permanently fail one chip (seeded pick when unspecified)."""
        from kubegpu_tpu.node.backend import CHIP_FAILED

        with self._lock:
            node, backend, chip = self._pick(node, chip_id)
            backend.set_chip_health(chip.chip_id, CHIP_FAILED)
            self.injected.append(("chip-kill", node, chip.chip_id, ""))
            return node, chip.chip_id

    def flap_chip(self, node: str | None = None,
                  chip_id: str | None = None, period: int = 2) -> tuple:
        """Start a 1-in-``period`` health flapper on one chip."""
        from kubegpu_tpu.node.backend import CHIP_DEGRADED

        with self._lock:
            node, backend, chip = self._pick(node, chip_id)
            backend.set_chip_flapper(chip.chip_id, CHIP_DEGRADED,
                                     period=period)
            self.injected.append(
                ("chip-flap", node, chip.chip_id, f"period={period}"))
            return node, chip.chip_id

    def cut_link(self, node: str | None = None,
                 chip_id: str | None = None,
                 direction: int | None = None) -> tuple:
        """Cut one ICI link (bit index into ``mesh.LINK_DIRS``; seeded
        pick among the chip's live links when unspecified). Cuts BOTH
        endpoints when the neighbor chip lives on a known backend — a
        physical link is shared hardware."""
        from kubegpu_tpu.topology.mesh import LINK_DIRS

        with self._lock:
            node, backend, chip = self._pick(node, chip_id)
            if direction is None:
                direction = self._rng.randrange(len(LINK_DIRS))
            mask = 1 << direction
            dead = dict(backend.link_health()).get(chip.chip_id, 0)
            backend.set_link_health(chip.chip_id, dead | mask)
            # the far endpoint sees the same cut, in the opposite
            # direction (LINK_DIRS pairs are (+,-) per axis: 0<->1 etc.)
            d = LINK_DIRS[direction]
            far = tuple(chip.coords[i] + d[i] for i in range(3))
            opposite = 1 << (direction ^ 1)
            for other_node in sorted(self._backends):
                other = self._backends[other_node]
                for c in other.inventory.chips:
                    if c.coords == far:
                        fdead = dict(other.link_health()).get(c.chip_id, 0)
                        other.set_link_health(c.chip_id, fdead | opposite)
            self.injected.append(
                ("link-down", node, chip.chip_id, f"dir={direction}"))
            return node, chip.chip_id, direction

    def plan(self, n: int, kinds: tuple = KINDS) -> list:
        """Materialize a deterministic schedule of ``n`` fault kinds
        (the targets are still drawn at injection time, from the same
        RNG, so plan+step is as reproducible as calling the injectors
        directly)."""
        with self._lock:
            return [self._rng.choice(list(kinds)) for _ in range(n)]

    def step(self, kind: str) -> tuple:
        """Apply one planned fault kind with seeded targeting."""
        if kind == "chip-kill":
            return self.kill_chip()
        if kind == "chip-flap":
            return self.flap_chip()
        if kind == "link-down":
            return self.cut_link()
        raise ValueError(f"unknown device fault kind: {kind}")


class ChaosProxy:
    """Duck-typed stand-in for the API client it wraps: every callable
    attribute goes through the chaos network first."""

    def __init__(self, net: ChaosNetwork, api, component: str,
                 config: ChaosConfig):
        self._net = net
        self._api = api
        self._component = component
        self._config = config

    def __getattr__(self, name: str):
        real = getattr(self._api, name)
        if not callable(real) or name.startswith("_") \
                or name in _PASSTHROUGH:
            return real

        def wrapper(*args, **kwargs):
            delay_s, duplicate = self._net.draw(
                self._component, name, self._config)
            if delay_s > 0:
                time.sleep(delay_s)
            result = real(*args, **kwargs)
            if duplicate:
                # at-least-once delivery: the duplicate's outcome (often
                # a Conflict on create, a no-op on idempotent verbs) is
                # the network's problem, not the caller's
                try:
                    real(*args, **kwargs)
                except Exception:
                    pass
            return result
        return wrapper

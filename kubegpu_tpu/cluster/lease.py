"""Lease-based coordination for the HA control plane.

Three pieces, layered:

``LeaseTable``
    TTL leases with steal-on-expiry — the server-side primitive (served
    by the API server; previously private to ``httpapi.serve_api``). A
    lease is (name, holder, expiry); ``acquire`` renews for the current
    holder, grants a vacant or expired lease to anyone, and refuses an
    unexpired lease held by someone else.

``Elector``
    One replica's view of one lease: acquire -> lead, renew at an
    interval, demote on a real denial or once the lease could have
    expired. Generalizes the lease-failover loop that previously lived
    inline in ``cmd/scheduler_main.py`` (and the reference's
    ``cmd/app/server.go:396-403,437-461``): a transient transport error
    at renewal neither crashes the replica nor demotes a leader whose
    lease is still within TTL — nobody else can take it until the TTL
    truly lapses, so tearing down early would just leave the cluster
    leaderless. Used for per-shard scheduler ownership and to make the
    NodeLifecycle controller singleton-elected instead of
    assumed-singleton.

``ShardCoordinator``
    N scheduler replicas each own one shard of the pod queue (by
    pod-name hash, ``shard_of``) and hold that shard's lease. Work
    stealing is lease-vacancy-driven: a replica also processes any
    shard whose lease currently has NO holder (its replica is dead or
    partitioned), and stops the moment the owner's renewals resume.
    Two replicas briefly processing the same shard during a handoff is
    safe by construction — the API server's optimistic-concurrency
    arbiter (`apiserver.bind_many`) rejects the loser's commit and the
    binder's forget+requeue path absorbs it.

Every clock here is monotonic (analysis rule: liveness/expiry decisions
must not move with wall-clock steps).
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from typing import Callable, FrozenSet, Optional

from kubegpu_tpu import metrics, obs
from kubegpu_tpu.analysis.explore import probe

log = logging.getLogger(__name__)

# A lease acquire over the wire: (name, holder, ttl seconds) -> granted.
AcquireFn = Callable[[str, str, float], bool]
# A lease holder query: name -> current holder, or None when vacant.
HolderFn = Callable[[str], Optional[str]]

SHARD_LEASE_PREFIX = "kgtpu-sched-shard"
LIFECYCLE_LEASE = "kgtpu-lifecycle"
REPAIR_LEASE = "kgtpu-repair"


def shard_of(pod_name: str, replicas: int) -> int:
    """Stable shard assignment by pod name. CRC32, not ``hash()``:
    the mapping must agree across replica *processes* (PYTHONHASHSEED
    randomizes ``hash`` per process)."""
    if replicas <= 1:
        return 0
    return zlib.crc32(pod_name.encode("utf-8")) % replicas


class LeaseTable:
    """TTL leases for leader election / shard ownership."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> (holder, expires_at by monotonic clock)
        self._leases: dict = {}

    def acquire(self, name: str, holder: str, ttl_s: float) -> bool:
        """Grant/renew: the current holder always renews; anyone takes a
        vacant or expired lease (steal-on-expiry); an unexpired lease
        held by someone else is refused."""
        probe("lease.acquire")
        with self._lock:
            now = time.monotonic()
            current = self._leases.get(name)
            if current is not None and current[1] > now \
                    and current[0] != holder:
                return False
            self._leases[name] = (holder, now + ttl_s)
            return True

    def holder(self, name: str) -> Optional[str]:
        with self._lock:
            current = self._leases.get(name)
            if current is None or current[1] <= time.monotonic():
                return None
            return current[0]

    def release(self, name: str, holder: str) -> bool:
        """Drop the lease iff ``holder`` still holds it — a clean
        shutdown hands the shard over immediately instead of making the
        successor wait out the TTL."""
        probe("lease.release")
        with self._lock:
            current = self._leases.get(name)
            if current is None or current[0] != holder:
                return False
            del self._leases[name]
            return True


class Elector:
    """Acquire/renew one lease; promote and demote through callbacks.

    ``acquire`` is any ``AcquireFn`` — ``HTTPAPIClient.acquire_lease``,
    ``InMemoryAPIServer.acquire_lease``, or a bare ``LeaseTable.acquire``
    — so the same elector drives in-process simulations and real
    multi-process replicas. ``tick()`` performs one renewal attempt;
    ``start()`` runs ticks at ttl/3 on a daemon thread.
    """

    def __init__(self, acquire: AcquireFn, name: str, holder: str,
                 ttl_s: float,
                 on_acquire: Optional[Callable[[], None]] = None,
                 on_lose: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.holder = holder
        self.ttl_s = ttl_s
        self._acquire = acquire
        self._on_acquire = on_acquire
        self._on_lose = on_lose
        self._clock = clock
        self._lock = threading.Lock()
        self._leading = False
        self._valid_until = 0.0
        self.transitions = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def leading(self) -> bool:
        with self._lock:
            return self._leading

    def tick(self) -> bool:
        """One renewal attempt. Stamps validity from BEFORE the round
        trip (the server's TTL starts when it grants, so counting from
        the reply would keep a leader ~one RTT past a lapse a standby
        can already take); a transient transport error keeps the leader
        leading while the last successful renewal is still within TTL."""
        asked_at = self._clock()
        granted: bool
        try:
            granted = bool(self._acquire(self.name, self.holder, self.ttl_s))
        except Exception:
            with self._lock:
                granted = self._leading and self._clock() < self._valid_until
            log.warning("lease %s: renewal transport error (%s grace)",
                        self.name, "within" if granted else "past",
                        exc_info=True)
            if granted:
                return True
        with self._lock:
            if granted:
                self._valid_until = asked_at + self.ttl_s
            was = self._leading
            self._leading = granted
        if granted and not was:
            self._count_transition()
            log.info("lease %s: %s became holder", self.name, self.holder)
            self._fire(self._on_acquire)
        elif not granted and was:
            self._count_transition()
            log.info("lease %s: %s lost the lease", self.name, self.holder)
            # losing a held lease mid-run is an anomaly worth evidence
            # (who was scheduling what when leadership moved); the
            # flight recorder is inert unless configured
            obs.FLIGHT.trigger("lease_lost", key=self.name,
                               holder=self.holder)
            self._fire(self._on_lose)
        return granted

    def _count_transition(self) -> None:
        """Count one leadership transition, guarded: ``stop()`` runs on
        the owner thread while ``tick()`` may still be finishing a
        renewal on the elector thread — an unguarded ``+=`` between them
        loses updates (a racer-rule true positive)."""
        probe("lease.count_transition")
        metrics.LEASE_TRANSITIONS.inc()
        with self._lock:
            self.transitions += 1

    @staticmethod
    def _fire(callback: Optional[Callable[[], None]]) -> None:
        if callback is None:
            return
        try:
            callback()
        except Exception:
            # a crashing promote/demote hook must not kill the elector
            # loop — the lease state machine is what keeps HA converging
            log.exception("elector callback failed")

    def start(self, interval_s: Optional[float] = None) -> None:
        interval = interval_s if interval_s is not None else self.ttl_s / 3.0

        def loop() -> None:
            obs.register_thread("elector")
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:
                    log.exception("elector tick failed")
                self._stop.wait(interval)

        # racer: single-writer -- start()/stop() are owner-thread calls
        self._stop = threading.Event()
        # racer: single-writer -- stop() joins the loop before clearing
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"elector-{self.name}")
        self._thread.start()

    def stop(self, demote: bool = True) -> None:
        """Stop the loop. ``demote`` fires ``on_lose`` when leading —
        a clean shutdown must tear down what promotion built."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            was = self._leading
            self._leading = False
        if demote and was:
            self._count_transition()
            self._fire(self._on_lose)


class ShardCoordinator:
    """One replica's shard ownership: hold shard ``shard`` of
    ``replicas`` via its lease, and steal work from shards whose lease
    is vacant.

    ``owns(pod_name)`` is the filter the scheduler consults per pod —
    a cheap set lookup against the ownership computed by the last
    ``tick()``. Ownership changes call ``on_change`` (the scheduler
    wires this to a queue wake-up so freshly-stolen pods are retried
    immediately instead of waiting out their park delay).
    """

    def __init__(self, lease_api: object, shard: int, replicas: int,
                 holder: str, ttl_s: float = 5.0,
                 lease_prefix: str = SHARD_LEASE_PREFIX,
                 on_change: Optional[Callable[[], None]] = None) -> None:
        self.shard = shard
        self.replicas = max(1, replicas)
        self.holder = holder
        self.ttl_s = ttl_s
        self.lease_prefix = lease_prefix
        self._holder_fn: Optional[HolderFn] = \
            getattr(lease_api, "lease_holder", None)
        self._release_fn = getattr(lease_api, "release_lease", None)
        # public: the scheduler is typically built AFTER the coordinator
        # (it needs ``owns`` at construction), then wires its queue
        # wake-up in here
        self.on_change = on_change
        self._lock = threading.Lock()
        self._owned: FrozenSet[int] = frozenset()
        acquire: AcquireFn = getattr(lease_api, "acquire_lease")
        self._elector = Elector(acquire, f"{lease_prefix}-{shard}", holder,
                                ttl_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def lease_name(self, shard: int) -> str:
        return f"{self.lease_prefix}-{shard}"

    def owns(self, pod_name: str) -> bool:
        with self._lock:
            owned = self._owned
        return shard_of(pod_name, self.replicas) in owned

    def owned_shards(self) -> FrozenSet[int]:
        with self._lock:
            return self._owned

    def tick(self) -> FrozenSet[int]:
        """Renew the own-shard lease, then scan the other shards'
        holders: a vacant lease means its replica stopped renewing —
        steal that shard's WORK (not its lease: the moment the rightful
        owner's renewals resume, its holder reappears and the thief
        stands down, with no lease tug-of-war)."""
        owned = set()
        if self._elector.tick():
            owned.add(self.shard)
        for other in range(self.replicas):
            if other == self.shard:
                continue
            if self._holder_fn is None:
                continue
            try:
                current = self._holder_fn(self.lease_name(other))
            except Exception:
                # unknown: never steal on a blind transport — wrongly
                # assuming vacancy would double-process a live shard
                log.debug("holder query for shard %d failed; not "
                          "stealing", other, exc_info=True)
                continue
            if current is None or current == self.holder:
                owned.add(other)
        frozen = frozenset(owned)
        with self._lock:
            changed = frozen != self._owned
            self._owned = frozen
        if changed:
            log.info("shard coordinator %s: owns shards %s", self.holder,
                     sorted(frozen))
            Elector._fire(self.on_change)
        return frozen

    def start(self, interval_s: Optional[float] = None) -> None:
        interval = interval_s if interval_s is not None else self.ttl_s / 3.0

        def loop() -> None:
            obs.register_thread("elector")
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:
                    log.exception("shard coordinator tick failed")
                self._stop.wait(interval)

        # racer: single-writer -- start()/stop() are owner-thread calls
        self._stop = threading.Event()
        # racer: single-writer -- stop() joins the loop before clearing
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"shard-coord-{self.shard}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._elector.stop(demote=False)
        # hand the shard over immediately: a clean shutdown must not make
        # the stealing replica wait out the full TTL
        if self._release_fn is not None:
            try:
                self._release_fn(self.lease_name(self.shard), self.holder)
            except Exception:
                log.debug("shard lease release failed (successor waits "
                          "out the TTL)", exc_info=True)
        with self._lock:
            self._owned = frozenset()

"""HTTP + streaming transport for the API server: the control plane wire.

The reference's components communicate *only* through the Kubernetes API
server (SURVEY.md §1); this module gives the framework the same property
across processes: `serve_api` exposes an `InMemoryAPIServer` over HTTP, and
`HTTPAPIClient` implements the identical client surface (get/patch nodes,
pods, bind, watch), so the node agent, scheduler, and runtime hook run as
separate OS processes wired only by the API endpoint.

Two negotiated wires share one port and one route table:

* **json** — request/response JSON over HTTP/1.1 keep-alive, watch as a
  long-poll on ``GET /watch?since=<seq>``. The debug wire: curl-able,
  and the fallback every old client keeps working on.
* **stream** (``HTTPAPIClient(wire="stream")``) — after an ``Upgrade:
  kgtpu-stream`` handshake the same socket switches to length-prefixed
  CRC-checked frames (`cluster/stream.py`) carrying the compact binary
  codec (`core/codec.py`): requests and responses multiplex on
  per-thread connections with no HTTP header parse per round trip, and
  watch becomes server PUSH — the event log encodes each coalesced
  batch ONCE and fans the identical frame bytes out to every
  subscriber, instead of a long-poll re-request + per-watcher re-encode
  per batch. A client whose upgrade is answered with plain HTTP
  negotiates down to json transparently.

Routes (shared by both wires):

    GET    /healthz
    GET    /nodes            | POST /nodes        | GET/DELETE /nodes/<name>
    PATCH  /nodes/<name>/metadata
    GET    /pods[?node=...]  | POST /pods         | GET/DELETE /pods/<name>
    PUT    /pods/<name>/annotations
    POST   /pods/<name>/bind            {"node": ...}
    POST   /bindmany                    {"bindings": {...}, "annotations": {...}}
    GET    /pvcs | POST /pvcs | GET/DELETE /pvcs/<name>   (likewise /pvs)
    POST   /bindvolume                  {"pv": ..., "pvc": ...}
    GET    /watch?since=<seq>           -> {"events": [[seq, kind, event, obj]...]}
    POST   /leases/<name>               {"holder":..., "ttl":...} -> 200/409
    GET    /metrics                     (Prometheus exposition, text/plain)
    GET    /metrics/history?window_s=N  (windowed metric deltas/percentiles)
    GET    /debug/traces | /debug/pod/<name> | /debug/profile

Leases implement the scheduler's HA leader election (reference:
`cmd/app/server.go:396-403,437-461`).
"""

from __future__ import annotations

import http.client
import json
import logging
import random
import socket
import threading
import time
import urllib.error
import urllib.parse
from bisect import bisect_right
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubegpu_tpu import metrics, obs
from kubegpu_tpu.analysis.explore import probe
from kubegpu_tpu.cluster import stream
from kubegpu_tpu.cluster.apf import APFDispatcher, TooManyRequests
from kubegpu_tpu.cluster.apiserver import (Conflict, InMemoryAPIServer,
                                           NotFound, QuotaExceeded)
from kubegpu_tpu.cluster.lease import LeaseTable  # noqa: F401  (re-export:
# the lease primitive moved to cluster/lease.py; the API server owns its
# own table now and the routes below delegate to it)
from kubegpu_tpu.core import codec


def coalesce_events(events: list) -> tuple:
    """Fold one watch window per the informer compression table:
    added+modified -> added(latest), modified+modified -> modified(latest),
    added+deleted -> nothing (the client never saw the object),
    modified+deleted -> deleted. Chains never merge ACROSS a deleted
    event — a re-create is a new object history, and collapsing
    delete+add into a modify would skip the consumer's teardown path.

    Cross-object order follows each chain's first event, and a merged
    chain carries its LAST event's sequence number and object — so
    per-object versions only ever move forward and the client's
    seq-resume cursor lands exactly where a full replay would have put
    it. Returns ``(events, folded_count)``."""
    out: list = []
    tail: dict = {}  # (kind, object name) -> index of its chain in out
    folded = 0
    for ev in events:
        seq, kind, etype, obj = ev
        name = (obj.get("metadata") or {}).get("name") \
            if isinstance(obj, dict) else None
        key = (kind, name)
        idx = tail.get(key)
        prev = out[idx] if idx is not None else None
        if name is None or prev is None or prev[2] == "deleted" or \
                etype not in ("modified", "deleted"):
            tail[key] = len(out)
            out.append(ev)
            continue
        if etype == "modified":
            out[idx] = (seq, kind, prev[2], obj)
            folded += 1
        elif prev[2] == "added":
            out[idx] = None
            tail.pop(key)
            folded += 2
        else:
            out[idx] = (seq, kind, "deleted", obj)
            folded += 1
    return [e for e in out if e is not None], folded


class _StreamSubscriber:
    """One push watcher on the stream wire: a bounded outbound frame
    queue drained by its own writer thread, so a slow or dead consumer
    can neither wedge the fan-out pump nor any other watcher. Overflow
    or a send fault kills the CONNECTION (never the server): the client
    reconnects and resumes seq-exact from its cursor, which is the same
    recovery the JSON long-poll already has."""

    MAX_QUEUED = 256

    def __init__(self, send, cursor: int, kinds, batch_s: float,
                 threaded: bool = True, on_dead=None):
        self._send = send          # callable(frame bytes) -> None
        self.cursor = cursor       # last seq delivered; PUMP-owned
        self.kinds = frozenset(kinds) if kinds else None
        self.batch_s = batch_s
        # called exactly once on the alive->dead transition (severs the
        # connection, so the client notices IMMEDIATELY instead of
        # sitting out its read timeout on a socket nobody feeds)
        self._on_dead = on_dead
        self._lock = threading.Condition()
        self._queue: deque = deque()
        self._dead = False
        self._inflight = False  # an inline send is on the socket
        self._thread = None
        if threaded:
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True, name="watch-push")
            self._thread.start()

    def offer(self, data: bytes) -> None:
        """Hand one encoded frame to this subscriber; the fast path
        sends INLINE (the common case: queue empty, socket writable —
        one thread handoff fewer on the push latency path), falling back
        to the writer-thread queue whenever a send is already in
        flight. The server caps the socket's send timeout, so a wedged
        consumer costs the pump one bounded send before it is severed —
        it can never stall the fan-out indefinitely."""
        probe("stream.offer")
        if self._thread is None:
            # direct mode (unit tests, the interleaving explorer): the
            # caller IS the delivery thread
            try:
                self._send(data)
            except Exception:
                self._die()
            return
        with self._lock:
            if self._dead:
                return
            if self._queue or self._inflight:
                overflow = len(self._queue) >= self.MAX_QUEUED
                if not overflow:
                    self._queue.append(data)
                    self._lock.notify_all()
                    return
                # a consumer this far behind will never catch up by
                # buffering more; sever it and let resume do its job
            else:
                self._inflight = True
                overflow = False
        if overflow:
            self._die()
            return
        try:
            self._send(data)  # outside locks; socket timeout bounds it
        except Exception:
            self._die()
        finally:
            with self._lock:
                self._inflight = False
                self._lock.notify_all()

    def is_dead(self) -> bool:
        with self._lock:
            return self._dead

    def stop(self) -> None:
        self._die()
        # a dead subscriber's writer exits on the notify; join it so
        # teardown leaves no writer thread behind (self-join guarded:
        # _die may be invoked from the writer's own send failure)
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)

    def _die(self) -> None:
        """Alive->dead transition: wake the writer and fire ``on_dead``
        exactly once, OUTSIDE the lock (it closes a socket) — severing
        the connection is what turns 'silently starved watcher' into an
        immediate client reconnect + seq-exact resume."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
            self._lock.notify_all()
        cb = self._on_dead
        if cb is not None:
            try:
                cb()
            except Exception:
                pass  # the connection may already be gone

    def _writer_loop(self):
        obs.register_thread("stream-pump")
        while True:
            with self._lock:
                while (not self._queue or self._inflight) \
                        and not self._dead:
                    self._lock.wait()
                if self._dead:
                    return
                data = self._queue.popleft()
                # claim the socket so a concurrent offer() cannot jump
                # the queue with an inline send (frames must stay in
                # cursor order per subscriber)
                self._inflight = True
            try:
                self._send(data)  # blocking socket write, outside locks
            except Exception:
                with self._lock:
                    self._inflight = False
                self._die()
                return
            with self._lock:
                self._inflight = False
                self._lock.notify_all()


class _EventLog:
    """Bounded sequence-numbered event log backing /watch long-polls.

    With a ``wal`` (cluster/wal.py), the log is durable: every record is
    appended to the WAL *before* any watcher can see it, the apiserver's
    object state is rebuilt from snapshot+replay on construction, and
    the sequence space continues across a process restart — so a client
    resuming with ``since=seq`` gets exactly the events it missed.
    ``floor`` is the highest sequence number no longer replayable
    (snapshot compaction or the in-memory trim); a client presenting an
    older ``since`` is answered with a full-relist signal instead of a
    silent gap.

    With ``attach=False`` the log records nothing on its own: it is the
    watch-cache proxy's downstream window (cluster/proxy.py), fed
    UPSTREAM events carrying their upstream sequence numbers through
    :meth:`reset` / :meth:`ingest` / :meth:`backfill` — the seq space
    stays the apiserver's own (global, WAL-continued), which is what
    keeps resume seq-exact when a client migrates between a proxy
    replica and the apiserver."""

    def __init__(self, api: InMemoryAPIServer, limit: int = 10000,
                 wal=None, attach: bool = True):
        import os as _os

        self._lock = threading.Condition()
        self._events: list = []
        self._seq = 0
        self._floor = 0
        self.limit = limit
        self._wal = wal
        self._api = api
        # stream-wire push fan-out (add_stream_subscriber): subscribers,
        # their pump thread, and the encode-once accounting the tests
        # (and the 4k-node scaling story) assert on
        self._subs: list = []
        self._pump_thread = None
        self._pump_stop = False
        self.stream_encodes = 0   # batches encoded (once per window)
        self.stream_deliveries = 0  # frames offered across subscribers
        # stream identity: WAL-backed logs keep theirs across restarts
        # (sequence continuity is real); a volatile log mints a fresh
        # one per life, so clients can detect a restart even when the
        # new sequence space overlaps their old cursor
        self.epoch = wal.stream_epoch() if wal is not None \
            else _os.urandom(8).hex()
        if wal is not None:
            # recovery BEFORE the watcher registers: replay must not
            # re-log itself, and clients must never see partial state
            last_seq, floor, tail = wal.recover(api)
            self._seq = last_seq
            self._floor = floor
            self._events = list(tail)[-limit:]
            if len(tail) > limit:
                self._floor = self._events[0][0] - 1
        if attach:
            api.add_watcher(self._record)

    # Recent events carried INSIDE each snapshot: they are already
    # reflected in the snapshotted state (never re-applied on recovery)
    # but extend the watch-resume window below the compaction point, so
    # a client up to this many events behind the final pre-crash
    # snapshot still resumes seq-exact instead of relisting.
    SNAPSHOT_TAIL = 256

    def seq(self) -> int:
        with self._lock:
            return self._seq

    def floor(self) -> int:
        with self._lock:
            return self._floor

    def tail(self, k: int) -> list:
        with self._lock:
            return list(self._events[-k:]) if k > 0 else []

    def stream_subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    # ---- proxy-mode ingest (cluster/proxy.py, attach=False) ---------------

    def reset(self, head_seq: int, epoch: str) -> None:
        """Adopt an upstream position wholesale: drop any window held,
        continue from the upstream head under the upstream epoch. Used
        at proxy sync, and again whenever the upstream relists us (its
        own window is gone, so ours is garbage too) — every downstream
        cursor below the new head then receives the same honest relist
        signal the upstream gave, and an epoch change propagates so
        clients detect a non-durable apiserver restart THROUGH the
        proxy exactly as they would directly."""
        with self._lock:
            self._events = []
            self._seq = head_seq
            self._floor = head_seq
            self.epoch = epoch
            self._lock.notify_all()

    def ingest(self, events: list, head_seq: int) -> None:
        """Record one upstream watch batch WITH its upstream sequence
        numbers. The batch arrives coalesced, so chain seqs can
        interleave across objects — sort before appending to keep the
        log bisectable; per-object order survives (an object's seqs
        only move forward). Trimming advances the floor exactly like
        the recording path."""
        with self._lock:
            batch = sorted((tuple(ev) for ev in events
                            if ev[0] > self._seq),
                           key=lambda ev: ev[0])
            self._events.extend(batch)
            if head_seq > self._seq:
                self._seq = head_seq
            if len(self._events) > self.limit:
                drop = len(self._events) - self.limit
                self._floor = self._events[drop - 1][0]
                self._events = self._events[drop:]
            self._lock.notify_all()

    def backfill(self, events: list, new_floor: int) -> None:
        """Extend the replayable window DOWNWARD: a downstream watcher
        presented a cursor below our floor and the upstream — whose
        window is deeper — replayed the gap. Only events below our
        current first seq prepend (the rest are already here); a
        coalesced chain whose merged seq landed inside our window is
        dropped with nothing lost — watch events carry whole objects,
        so the in-window event already holds that object's state. The
        floor drops to ``new_floor`` so the watcher resumes seq-exact
        instead of relisting."""
        with self._lock:
            first = self._events[0][0] if self._events else self._seq + 1
            prefix = sorted((tuple(ev) for ev in events
                             if new_floor < ev[0] < first),
                            key=lambda ev: ev[0])
            self._events = prefix + self._events
            self._floor = min(self._floor, new_floor)
            self._lock.notify_all()

    def _record(self, kind, event, obj):
        # self._wal is set once in __init__ and never reassigned — it is
        # configuration, not guarded state (and never written under the
        # lock, so lock-discipline does not flag it)
        wal = self._wal
        with self._lock:
            self._seq += 1
            seq = self._seq
            if wal is not None:
                # write-ahead: durable before any watcher is woken
                wal.append(seq, kind, event, obj)
            self._events.append((seq, kind, event, obj))
            if len(self._events) > self.limit:
                drop = len(self._events) - self.limit
                self._floor = self._events[drop - 1][0]
                self._events = self._events[drop:]
            self._lock.notify_all()
        if wal is not None and kind == "pod":
            # continue the mutation's trace through durability: pod
            # records only, and only when a span context is active (a
            # traced bind reaching the WAL) — the steady watch stream
            # and a traced request's side-writes (Events, PVC flips)
            # must not flood the bounded ring
            name = (obj.get("metadata") or {}).get("name") \
                if isinstance(obj, dict) else None
            if name is not None and obs.parent_for(name) is not None:
                obs.event("wal_append", pod=name, proc="apiserver",
                          event=event, seq=seq)
        if wal is not None and wal.due_for_snapshot():
            # Outside the event-log lock (state dump -> event-log seq is
            # the apiserver-first order every mutator already takes; the
            # reverse here would be an inversion). The caller is the
            # mutator's notify, so its reentrant apiserver lock is still
            # held and (state, seq) is exactly this record's cut.
            state, snap_seq = self._api.snapshot_with(self.seq)
            wal.snapshot(state, snap_seq, tail=self.tail(self.SNAPSHOT_TAIL))

    def since(self, seq: int, timeout: float = 10.0, batch_s: float = 0.0,
              kinds: frozenset | None = None):
        """Events after ``seq``, coalesced per-object. ``batch_s`` > 0
        lingers that long after the first pending event so a burst in
        progress rides THIS response instead of costing another poll;
        ``kinds`` narrows the stream server-side (a scheduler that never
        consumes Event records must not pay their encode/decode).
        Returns ``(events, latest_seq, folded_count, relist)`` — the
        resume contract is unchanged: every returned event keeps a
        sequence number > ``seq``, and ``latest_seq`` advances the
        cursor past anything folded away or filtered out. ``relist``
        is True when ``seq`` falls outside the replayable window and
        the caller must fall back to a full list."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                if seq < self._floor or seq > self._seq:
                    # outside the replayable window — below the floor
                    # (compaction/trim, possibly having moved WHILE this
                    # poll waited: the check lives under the serving
                    # lock so a concurrent trim cannot open a silent
                    # gap) or beyond the current sequence (a cursor from
                    # another server life): the caller must relist
                    return [], self._seq, 0, True
                out = self._window_locked(seq, kinds)
                if out:
                    if batch_s > 0:
                        end = min(time.monotonic() + batch_s, deadline)
                        while time.monotonic() < end:
                            self._lock.wait(end - time.monotonic())
                        out = self._window_locked(seq, kinds)
                    out, folded = coalesce_events(out)
                    return out, self._seq, folded, False
                if time.monotonic() >= deadline:
                    return [], self._seq, 0, False
                self._lock.wait(min(0.5, deadline - time.monotonic()))

    def _window_locked(self, seq: int, kinds) -> list:
        """Events after ``seq`` (kind-filtered), bisected instead of
        scanned: the log is seq-ordered and holds up to ``limit``
        entries, and a full scan per poll/push was the serving path's
        hidden O(log size) tax. Caller holds ``self._lock``."""
        idx = bisect_right(self._events, seq, key=lambda e: e[0])
        window = self._events[idx:]
        if kinds is None:
            return window
        return [e for e in window if e[1] in kinds]

    # ---- stream-wire push fan-out ------------------------------------------

    PING_EVERY_S = 5.0

    def add_stream_subscriber(self, send, since: int, kinds=None,
                              batch_s: float = 0.0,
                              threaded: bool = True,
                              on_dead=None) -> _StreamSubscriber:
        """Register a push watcher: ``send(frame bytes)`` receives every
        coalesced batch after ``since``. With ``threaded`` (production)
        the subscriber drains through its own writer thread and a shared
        pump thread runs the fan-out; tests and explorer scenarios pass
        ``threaded=False`` and drive :meth:`pump_once` themselves.
        ``on_dead`` fires once when the subscriber is severed (overflow
        or send fault) — the transport closes the connection there so
        the client reconnects immediately."""
        probe("stream.subscribe")
        sub = _StreamSubscriber(send, since, kinds, batch_s,
                                threaded=threaded, on_dead=on_dead)
        with self._lock:
            self._subs.append(sub)
            if threaded and self._pump_thread is None and \
                    not self._pump_stop:
                self._pump_thread = threading.Thread(
                    target=self._pump_loop, daemon=True,
                    name="watch-fanout")
                self._pump_thread.start()
            self._lock.notify_all()
        return sub

    def remove_stream_subscriber(self, sub: _StreamSubscriber) -> None:
        probe("stream.unsubscribe")
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
        sub.stop()

    def stop_stream(self) -> None:
        """Tear down the fan-out (server shutdown): stops the pump and
        every subscriber's writer thread, and JOINS them — a "stopped"
        stream with its pump still draining a wait was the unjoined-
        thread path the lifecycle work closed."""
        with self._lock:
            self._pump_stop = True
            subs = list(self._subs)
            self._subs = []
            pump = self._pump_thread
            self._pump_thread = None
            self._lock.notify_all()
        for sub in subs:
            sub.stop()
        if pump is not None and pump is not threading.current_thread():
            pump.join(timeout=5.0)

    def _pump_loop(self):
        obs.register_thread("stream-pump")
        while True:
            with self._lock:
                if self._pump_stop:
                    return
            self.pump_once(wait_s=self.PING_EVERY_S)

    def pump_once(self, wait_s: float = 0.0) -> int:
        """One fan-out pass: wait up to ``wait_s`` for any subscriber to
        fall behind the log head, then compute each lagging subscriber's
        window, encode every distinct ``(kinds, cursor)`` window exactly
        ONCE, and offer the identical frame bytes to each subscriber at
        that window — the per-watcher re-encode the long-poll wire pays
        is gone. A wait that expires idle pings every subscriber
        instead (liveness + dead-socket detection). Returns the number
        of frames offered."""
        probe("stream.pump")
        deadline = time.monotonic() + wait_s
        with self._lock:
            while True:
                self._subs = [s for s in self._subs if not s.is_dead()]
                behind = [s for s in self._subs if s.cursor != self._seq]
                if behind or self._pump_stop:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._lock.wait(min(0.5, remaining))
            if self._pump_stop:
                return 0
            linger = max((s.batch_s for s in behind), default=0.0)
            if behind and linger > 0:
                # ride a burst in progress: linger so the window folds
                # into one fuller frame instead of N thin ones
                end = time.monotonic() + linger
                while time.monotonic() < end:
                    self._lock.wait(end - time.monotonic())
                behind = [s for s in self._subs
                          if not s.is_dead() and s.cursor != self._seq]
            seq = self._seq
            floor = self._floor
            epoch = self.epoch
            events = []
            if behind:
                in_window = [s.cursor for s in behind
                             if floor <= s.cursor <= seq]
                if in_window:
                    # one bisected slice covering every lagging cursor —
                    # never a full copy of the bounded log
                    idx = bisect_right(self._events, min(in_window),
                                       key=lambda e: e[0])
                    events = self._events[idx:]
            subs = list(self._subs)
        if not behind:
            ping = stream.encode_frame(stream.PING, 0, b"")
            for sub in subs:
                sub.offer(ping)
            return 0
        # Encode outside the event-log lock: mutators must never stall
        # behind a fan-out pass. The wall-clock stamp rides the frame so
        # the receiving process can measure push lag; wall clock on
        # purpose (cross-process stamp, like the advertiser heartbeat).
        now_ts = time.time()  # analysis: disable=monotonic-time -- cross-process push-lag stamp, like the heartbeat annotation
        sent = 0
        relist_frame = None
        cache: dict = {}    # (kinds, cursor) -> frame
        encoded: dict = {}  # filtered-window signature -> frame
        for sub in behind:
            if sub.cursor < floor or sub.cursor > seq:
                # outside the replayable window (compaction/trim, or a
                # cursor from another server life): explicit relist
                # signal, exactly like the long-poll contract
                if relist_frame is None:
                    payload = codec.encode_watch_batch(
                        [], seq, relist=True, epoch=epoch,
                        ts=now_ts)
                    relist_frame = stream.encode_frame(
                        stream.PUSH, 0, payload)
                sub.offer(relist_frame)
                sub.cursor = seq
                sent += 1
                continue
            key = (sub.kinds, sub.cursor)
            frame = cache.get(key)
            if frame is None:
                window = [e for e in events
                          if e[0] > sub.cursor
                          and (sub.kinds is None or e[1] in sub.kinds)]
                # Distinct (kinds, cursor) cohorts whose FILTERED
                # windows coincide — cursors straddling only
                # filtered-out events, or different kind filters
                # passing the same events — must share one encode: the
                # signature keys the frame by the events actually
                # delivered (seqs are unique, so equal seq tuples mean
                # equal windows), so steady-state fan-out encodes once
                # TOTAL, not once per cursor cohort.
                sig = tuple(e[0] for e in window)
                frame = encoded.get(sig)
                if frame is None:
                    window, folded = coalesce_events(window)
                    t0 = time.perf_counter()
                    payload = codec.encode_watch_batch(
                        window, seq, coalesced=folded, epoch=epoch,
                        ts=now_ts)
                    frame = stream.encode_frame(stream.PUSH, 0, payload)
                    metrics.FRAME_ENCODE_MS.observe(
                        (time.perf_counter() - t0) * 1e3)
                    self.stream_encodes += 1
                    encoded[sig] = frame
                cache[key] = frame
            sub.offer(frame)
            self.stream_deliveries += 1
            sub.cursor = seq
            sent += 1
        return sent


# Raw-text response envelope: a route returning
# {RAW_CONTENT_TYPE: ..., RAW_TEXT: ...} is unwrapped by the JSON-wire
# HTTP handler into a plain text body with that content type (the
# Prometheus exposition must be scrapeable, not JSON-wrapped); the
# stream wire delivers the envelope dict unchanged.
RAW_CONTENT_TYPE = "__content_type__"
RAW_TEXT = "__text__"


def _split_path(path: str) -> tuple:
    """``"/pods?node=n1" -> (["pods"], {"node": "n1"})`` — one parser
    for both wires' route strings."""
    parts = [p for p in path.split("?")[0].split("/") if p]
    query: dict = {}
    if "?" in path:
        for kv in path.split("?", 1)[1].split("&"):
            if "=" in kv:
                k, v = kv.split("=", 1)
                query[k] = v
    return parts, query


def _error_body(e: Exception) -> dict:
    """The error payload both wires send for typed errors (NotFound /
    Conflict / QuotaExceeded / TooManyRequests) — per-pod conflict/bind
    detail and the front door's advised retry_after_s included, so the
    client reconstructs the identical typed error either wire carried
    (the binder's conflict handling and the retry policy's advised
    backoff both depend on it)."""
    body = {"error": str(e)}
    if getattr(e, "per_pod", None):
        body["per_pod"] = e.per_pod
    retry_after = getattr(e, "retry_after_s", None)
    if retry_after:
        body["retry_after_s"] = retry_after
    return body


def _route_request(api: InMemoryAPIServer, log: _EventLog, method: str,
                   parts: list, query: dict, body):
    """The transport-neutral route table: returns ``(status, object)``
    or raises NotFound/Conflict for the transport to map. Both the HTTP
    handler and the stream dispatcher call THIS — one route surface,
    two framings."""
    if parts == ["healthz"]:  # analysis: disable=wire-contract -- curl/monitoring liveness probe; no package client consumes it
        return 200, {"ok": True}
    if parts == ["debug", "traces"] and method == "GET":  # analysis: disable=wire-contract -- operator debug surface (curl/Perfetto), deliberately client-less
        # this process's span ring, Perfetto-loadable
        return 200, obs.chrome_trace()
    if parts == ["debug", "profile"] and method == "GET":
        # the sampling profiler's attribution table + collapsed stacks
        # (curl-only, waived with the rest of /debug above)
        return 200, obs.profile_status()
    if parts == ["metrics", "history"] and method == "GET":  # analysis: disable=wire-contract -- operator/monitoring surface (curl), deliberately client-less
        # the metrics time-series' windowed summary (counter rates,
        # windowed histogram percentiles, gauge envelopes)
        return 200, obs.metrics_history(
            window_s=float(query.get("window_s", 300.0)),
            limit=int(query.get("limit", 0)))
    if parts == ["metrics"] and method == "GET":
        # first-class Prometheus exposition (the /metrics segment's
        # curl-only waiver rides the /metrics/history route above): the
        # HTTP handler unwraps
        # the raw-text envelope into a text/plain body for scrapers;
        # stream-wire callers receive the envelope dict as-is
        return 200, {RAW_CONTENT_TYPE: "text/plain; version=0.0.4",
                     RAW_TEXT: metrics.prometheus_text()}
    if parts[:2] == ["debug", "pod"] and len(parts) == 3 \
            and method == "GET":
        return 200, obs.explain_pod(urllib.parse.unquote(parts[2]))
    if parts == ["watch"]:
        kinds = frozenset(query["kinds"].split(",")) \
            if query.get("kinds") else None
        events, seq, folded, relist = log.since(
            int(query.get("since", 0)),
            float(query.get("timeout", 10.0)),
            float(query.get("batch", 0.0)), kinds)
        out = {"events": events, "seq": seq,
               "coalesced": folded, "epoch": log.epoch}
        if relist:
            # the cursor falls outside the replayable window
            # (pre-snapshot/trimmed, or from another server life): the
            # delta stream has a gap, so tell the client to relist
            # instead of resuming silently wrong
            out["relist"] = True
        return 200, out
    if parts and parts[0] == "leases" and len(parts) == 2:
        if method == "POST":
            ok = api.acquire_lease(parts[1], body["holder"],
                                   float(body.get("ttl", 15.0)))
            return (200 if ok else 409,
                    {"holder": api.lease_holder(parts[1])})
        if method == "GET":
            return 200, {"holder": api.lease_holder(parts[1])}
        if method == "DELETE":
            api.release_lease(parts[1], query.get("holder", ""))
            return 200, {}
    if parts and parts[0] == "nodes":
        if method == "GET" and len(parts) == 1:
            return 200, {"items": api.list_nodes()}
        if method == "POST" and len(parts) == 1:
            return 201, api.create_node(body)
        if method == "GET":
            return 200, api.get_node(parts[1])
        if method == "DELETE":
            api.delete_node(parts[1])
            return 200, {}
        if method == "PATCH" and parts[2:] == ["metadata"]:
            return 200, api.patch_node_metadata(parts[1], body)
    if parts == ["podannotations"] and method == "PUT":
        api.update_pod_annotations_many(body)
        return 200, {}
    if parts and parts[0] == "pods":
        if method == "GET" and len(parts) == 1:
            return 200, {"items": api.list_pods(
                node_name=query.get("node"),
                phase=query.get("phase"),
                bound=query.get("bound") in ("1", "true"))}
        if method == "POST" and len(parts) == 1:
            return 201, api.create_pod(body)
        if method == "GET":
            return 200, api.get_pod(parts[1])
        if method == "DELETE":
            api.delete_pod(parts[1])
            return 200, {}
        if method == "PUT" and parts[2:] == ["annotations"]:
            return 200, api.update_pod_annotations(parts[1], body)
        if method == "POST" and parts[2:] == ["bind"]:
            api.bind_pod(parts[1], body["node"])
            return 200, {}
    if parts == ["bindmany"] and method == "POST":
        api.bind_many(body["bindings"], body.get("annotations") or {})
        return 200, {}
    for kind, create, get_, list_, delete in (
            ("pvcs", api.create_pvc, api.get_pvc, api.list_pvcs,
             api.delete_pvc),
            ("pvs", api.create_pv, api.get_pv, api.list_pvs,
             api.delete_pv)):
        if parts and parts[0] == kind:
            if method == "GET" and len(parts) == 1:
                return 200, {"items": list_()}
            if method == "POST" and len(parts) == 1:
                return 201, create(body)
            if method == "GET" and len(parts) == 2:
                return 200, get_(parts[1])
            if method == "DELETE" and len(parts) == 2:
                delete(parts[1])
                return 200, {}
    if parts == ["bindvolume"] and method == "POST":
        api.bind_volume(body["pv"], body["pvc"])
        return 200, {}
    if parts and parts[0] == "quotas":
        if method == "GET" and len(parts) == 1:
            return 200, {"items": api.list_quotas()}
        if method == "PUT" and len(parts) == 2:
            return 200, api.set_quota(parts[1], body)
        if method == "DELETE" and len(parts) == 2:
            api.delete_quota(parts[1])
            return 200, {}
    if parts and parts[0] == "pdbs":
        if method == "GET" and len(parts) == 1:
            return 200, {"items": api.list_pdbs()}
        if method == "POST" and len(parts) == 1:
            return 201, api.create_pdb(body)
        if method == "DELETE" and len(parts) == 2:
            api.delete_pdb(parts[1])
            return 200, {}
    for kind, create, list_, delete in (
            ("services", api.create_service, api.list_services,
             api.delete_service),
            ("rcs", api.create_rc, api.list_rcs, api.delete_rc),
            ("rss", api.create_rs, api.list_rss, api.delete_rs),
            ("statefulsets", api.create_statefulset,
             api.list_statefulsets, api.delete_statefulset)):
        if parts and parts[0] == kind:
            if method == "GET" and len(parts) == 1:
                return 200, {"items": list_()}
            if method == "POST" and len(parts) == 1:
                return 201, create(body)
            if method == "DELETE" and len(parts) == 2:
                delete(parts[1])
                return 200, {}
    if parts == ["events"]:
        if method == "GET":
            return 200, {"items": api.list_events(
                involved_name=query.get("involved"))}
        if method == "POST":
            if isinstance(body, list):  # batched form
                api.record_events(body)
                return 200, {}
            return 201, api.record_event(
                body.get("kind", "Pod"), body["name"],
                body.get("type", "Normal"), body["reason"],
                body.get("message", ""))
    return 404, {"error": f"no route {method} /{'/'.join(parts)}"}


def serve_api(api: InMemoryAPIServer, host: str = "127.0.0.1", port: int = 0,
              wal=None, stream_wire: bool = True,
              apf: "APFDispatcher | None" = None):
    """Start serving; returns (ThreadingHTTPServer, base_url). The server
    runs on a daemon thread; ``server.shutdown()`` stops it COMPLETELY —
    live connections severed, the stream fan-out joined, the WAL handle
    closed, and the listening port released (a further
    ``server_close()`` is a harmless no-op). With ``wal``
    (a ``cluster.wal.WriteAheadLog``), the apiserver's state and watch
    log are recovered from disk before the first request is served, and
    every subsequent event is logged write-ahead — watch resume
    (``since=seq``) survives a crash. ``stream_wire=False`` refuses the
    ``kgtpu-stream`` upgrade (clients negotiate down to JSON). With
    ``apf`` (a ``cluster.apf.APFDispatcher``), every request on BOTH
    wires passes the priority-&-fairness front door before it reaches
    the route table: system traffic is exempt, tenant flows queue
    fairly, and shed work gets a typed 429 / REJECT frame carrying
    retry-after."""
    log = _EventLog(api, wal=wal)

    def _dispatch(method: str, parts: list, query: dict, body,
                  peer: str):
        """The ONE admission + routing path both wires share: a change
        to how requests pass the front door lands here once, or the
        wires drift."""
        if apf is not None:
            with apf.admit(method, parts, query, body, peer=peer):
                return _route_request(api, log, method, parts, query,
                                      body)
        return _route_request(api, log, method, parts, query, body)

    return _serve_transport(_dispatch, log, host=host, port=port,
                            stream_wire=stream_wire, wal=wal)


def _serve_transport(dispatch, log: _EventLog, host: str = "127.0.0.1",
                     port: int = 0, stream_wire: bool = True, wal=None,
                     on_subscribe=None, role: str = "apiserver"):
    """The transport half of :func:`serve_api`, parameterized over the
    admission + routing callable so the watch-cache proxy
    (cluster/proxy.py) serves the IDENTICAL dual-wire surface — same
    framing, same typed-exception -> status mapping, same REJECT flow
    control — over its own dispatch. ``on_subscribe(since)`` runs
    before a stream SUB registers (the proxy backfills a below-floor
    cursor from the deeper upstream window there); ``role`` labels the
    per-server request counter so a fronted apiserver's request rate is
    measurable apart from its proxies'. Returns ``(server, base_url)``;
    the server exposes its event log as ``server.event_log``."""

    def _dispatch(method: str, parts: list, query: dict, body,
                  peer: str):
        metrics.API_REQUESTS.labels(role).inc()
        return dispatch(method, parts, query, body, peer)

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 so keep-alive works: every _send sets Content-Length,
        # which is what lets the connection persist across requests — a
        # fresh TCP handshake per API call was the single largest fixed
        # cost on the transport bench. Nagle off: small JSON replies must
        # not wait out a delayed-ACK window.
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True

        def setup(self):
            super().setup()
            self.server._track_connection(self.connection)

        def finish(self):
            self.server._untrack_connection(self.connection)
            super().finish()

        def log_message(self, *args):  # quiet
            pass

        def _body(self):
            n = int(self.headers.get("Content-Length") or 0)
            return json.loads(self.rfile.read(n).decode()) if n else {}

        def _send(self, code: int, obj=None):
            content_type = "application/json"
            if isinstance(obj, dict) and RAW_CONTENT_TYPE in obj:
                content_type = obj[RAW_CONTENT_TYPE]
                data = str(obj.get(RAW_TEXT, "")).encode()
            else:
                data = json.dumps(obj if obj is not None else {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _route(self, method: str):
            parts, query = _split_path(self.path)
            try:
                body = self._body()
                # re-install the caller's span context (if any) so the
                # arbiter's and WAL's spans continue the caller's trace
                # across the process boundary
                with obs.remote_context(self.headers.get(obs.TRACE_HEADER)):
                    status, obj = _dispatch(method, parts, query, body,
                                            self.client_address[0])
                self._send(status, obj)
            except TooManyRequests as e:
                self._send(429, _error_body(e))
            except QuotaExceeded as e:
                self._send(403, _error_body(e))
            except NotFound as e:
                self._send(404, _error_body(e))
            except Conflict as e:
                self._send(409, _error_body(e))
            except (BrokenPipeError, ConnectionResetError):
                # client hung up mid-reply (e.g. a watcher killed during
                # its long-poll); there is nobody left to answer
                pass
            except Exception as e:  # noqa: BLE001
                try:
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})
                except (BrokenPipeError, ConnectionResetError):
                    pass

        def _serve_stream(self):
            """Switch this connection to the framed stream wire and
            serve it until the peer goes away (or poisons the stream).
            Runs in this connection's handler thread: requests dispatch
            through the SAME route table as HTTP, responses and watch
            pushes interleave under a per-connection write lock."""
            self.send_response(101, "Switching Protocols")
            self.send_header("Upgrade", stream.UPGRADE_TOKEN)
            self.send_header("Connection", "Upgrade")
            self.end_headers()
            self.wfile.flush()
            conn = self.connection
            wlock = threading.Lock()
            sub = None
            slog = logging.getLogger(__name__)
            try:
                while True:
                    try:
                        ftype, rid, payload = stream.read_frame(self.rfile)
                    except socket.timeout:
                        if sub is not None:
                            # subscribed connections are push channels:
                            # the client sends nothing after SUB, so an
                            # idle read timeout (set below to bound push
                            # sends) is a non-event at a frame boundary
                            continue
                        raise
                    if ftype == stream.PING:
                        continue
                    if ftype == stream.SUB:
                        if sub is not None:
                            raise stream.FrameError(
                                "duplicate subscription on one "
                                "connection")
                        args = codec.decode_value(payload)
                        if not isinstance(args, dict):
                            raise stream.FrameError(
                                "malformed subscribe frame")
                        kinds = args.get("kinds")
                        since = int(args.get("since") or 0)
                        if on_subscribe is not None:
                            # watch-cache proxy: a cursor below this
                            # log's floor may be replayable from the
                            # deeper upstream window — backfill BEFORE
                            # registering, so the subscriber resumes
                            # seq-exact instead of relisting
                            try:
                                on_subscribe(since)
                            except Exception:
                                slog.warning(
                                    "subscribe backfill from upstream "
                                    "failed; the pump will relist",
                                    exc_info=True)
                        # ack BEFORE registering: once the subscriber is
                        # in the fan-out, the pump may push immediately,
                        # and a PUSH must never overtake the ack on this
                        # connection (the client reads the ack first)
                        stream.send_frame(
                            conn, wlock, stream.RESP, rid,
                            codec.encode_response(
                                200, {"seq": log.seq(),
                                      "epoch": log.epoch}))
                        # bound every subsequent push send (a wedged
                        # consumer costs the fan-out one capped send,
                        # then is severed) — also caps this reader's
                        # idle blocking, handled above
                        conn.settimeout(10.0)

                        def sever(c=conn):
                            # a severed subscriber's client must notice
                            # NOW, not at its read timeout: kill the
                            # socket so reconnect + seq-exact resume
                            # engage immediately
                            try:
                                c.shutdown(socket.SHUT_RDWR)
                            except OSError:
                                pass
                            try:
                                c.close()
                            except OSError:
                                pass

                        sub = log.add_stream_subscriber(
                            send=lambda data: stream.send_raw(
                                conn, wlock, data),
                            since=since,
                            kinds=tuple(kinds) if kinds else None,
                            batch_s=float(args.get("batch") or 0.0),
                            on_dead=sever)
                        continue
                    if ftype != stream.REQ:
                        raise stream.FrameError(
                            f"unexpected frame type {ftype}")
                    t0 = time.perf_counter()
                    method, path, body, trace = codec.decode_request(
                        payload)
                    metrics.FRAME_DECODE_MS.observe(
                        (time.perf_counter() - t0) * 1e3)
                    parts, query = _split_path(path)
                    try:
                        with obs.remote_context(trace):
                            status, obj = _dispatch(
                                method, parts, query, body,
                                self.client_address[0])
                    except TooManyRequests as e:
                        # flow control is a first-class frame, not a
                        # response: the 429 body (with retry_after_s)
                        # rides a REJECT echoing the request id
                        status, obj = 429, _error_body(e)
                        stream.send_frame(conn, wlock, stream.REJECT,
                                          rid,
                                          codec.encode_response(status,
                                                                obj))
                        continue
                    except QuotaExceeded as e:
                        status, obj = 403, _error_body(e)
                    except NotFound as e:
                        status, obj = 404, _error_body(e)
                    except Conflict as e:
                        status, obj = 409, _error_body(e)
                    except Exception as e:  # noqa: BLE001
                        status, obj = 500, \
                            {"error": f"{type(e).__name__}: {e}"}
                    t0 = time.perf_counter()
                    data = codec.encode_response(status, obj)
                    metrics.FRAME_ENCODE_MS.observe(
                        (time.perf_counter() - t0) * 1e3)
                    stream.send_frame(conn, wlock, stream.RESP, rid,
                                      data)
            except stream.StreamClosed:
                pass
            except (stream.FrameError, codec.CodecError) as e:
                # hostile/torn frame: THIS connection is poisoned and
                # dies; the server and every other connection carry on
                slog.warning("stream connection poisoned: %s", e)
            except (ConnectionError, OSError):
                pass  # peer vanished / shutdown severed the socket
            finally:
                if sub is not None:
                    log.remove_stream_subscriber(sub)
                self.close_connection = True

        def do_GET(self):
            if self.path == stream.UPGRADE_PATH and stream_wire and \
                    (self.headers.get("Upgrade") or "").lower() == \
                    stream.UPGRADE_TOKEN:
                return self._serve_stream()
            self._route("GET")

        def do_POST(self):
            self._route("POST")

        def do_PUT(self):
            self._route("PUT")

        def do_PATCH(self):
            self._route("PATCH")

        def do_DELETE(self):
            self._route("DELETE")

    class Server(ThreadingHTTPServer):
        # handler threads must die with the process, and shutdown() must
        # sever live keep-alive connections: without that, a "restarted"
        # apiserver leaves ghost handler threads still serving the OLD
        # state to clients whose sockets never broke — the exact failure
        # a real process death cannot produce. Killing the sockets is
        # what makes restart observable (clients reconnect, and the
        # watch-resume / relist contract actually engages).
        daemon_threads = True

        def __init__(self, *args, **kwargs):
            self._client_conns: set = set()
            self._conn_lock = threading.Lock()
            super().__init__(*args, **kwargs)

        def _track_connection(self, conn) -> None:
            with self._conn_lock:
                self._client_conns.add(conn)

        def _untrack_connection(self, conn) -> None:
            with self._conn_lock:
                self._client_conns.discard(conn)

        def handle_error(self, request, client_address):
            pass  # severed-socket tracebacks are expected on shutdown

        def shutdown(self):
            super().shutdown()
            # stream-wire fan-out first: the pump and per-subscriber
            # writer threads must stop offering frames to sockets the
            # loop below is about to sever
            log.stop_stream()
            with self._conn_lock:
                conns = list(self._client_conns)
                self._client_conns.clear()
            for conn in conns:
                try:
                    # SHUT_RDWR first: close() alone does not wake a
                    # handler thread blocked in recv() on this socket
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            if wal is not None:
                # every mutator path is severed above; a "stopped"
                # apiserver must not keep its WAL file handle open
                # (apiserver_main also closes on its own exit path —
                # close() is idempotent — but tests and chaos restarts
                # call shutdown() directly and used to leak it)
                wal.close()
            # ...nor its port: serve_forever has returned by the time
            # super().shutdown() comes back, so releasing the listening
            # socket here is safe, and a second server_close() from a
            # caller following the old two-step contract is a no-op
            self.server_close()

    server = Server((host, port), Handler)
    # the log is closure state for the handlers; tests and the fan-out
    # bench need it by name (encode-once accounting, fake subscribers)
    server.event_log = log
    threading.Thread(target=server.serve_forever, daemon=True,
                     name=f"{role}-http").start()
    return server, f"http://{host}:{server.server_address[1]}"


class HTTPAPIClient:
    """Client with the same surface as `InMemoryAPIServer`, over HTTP.

    ``add_watcher`` spawns a long-poll thread replaying the server's event
    log, so informer-style consumers (the scheduler) work unchanged;
    ``add_batch_watcher`` delivers each poll's whole event batch to one
    callback so a consumer can apply it under a single cache lock.

    Requests ride a per-thread keep-alive connection (HTTP/1.1): the old
    urllib path paid a fresh TCP connect per call, which dominated the
    transport bench's per-request cost. With ``wire="stream"`` the same
    per-thread sockets carry framed binary requests instead (no HTTP
    header parse, no JSON encode per round trip) and the watch thread
    consumes server-pushed delta frames instead of long-polling; a
    server that answers the upgrade with plain HTTP negotiates the
    client back down to ``"json"`` permanently and everything keeps
    working.
    """

    # Verbs safe to resend when the transport (not the server) failed:
    # the request either never arrived or its reply was lost, and
    # re-applying it converges to the same state. POST stays single-shot
    # — a blind resend of a bind/create could double-apply.
    IDEMPOTENT_METHODS = frozenset({"GET", "PUT", "PATCH", "DELETE"})
    RETRY_ATTEMPTS = 3
    RETRY_BASE_S = 0.05
    RETRY_CAP_S = 0.5

    def __init__(self, base_url: str, timeout: float = 30.0,
                 watch_batch_s: float = 0.0,
                 watch_kinds: tuple | None = None,
                 wire: str = stream.WIRE_JSON,
                 transport_label: str | None = None):
        if wire not in (stream.WIRE_JSON, stream.WIRE_STREAM):
            raise ValueError(f"unknown wire {wire!r}")
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # transport_bytes_total{wire} attribution override: the proxy's
        # upstream client reports its hop as wire="proxy", so the
        # upstream leg of a fronted deployment is measurable apart from
        # the client legs (which keep their json/stream labels)
        self.transport_label = transport_label
        # the wire in effect; "stream" may negotiate down to "json" on
        # the first round trip against an upgrade-less server
        self.wire = wire
        # server-side linger per watch poll: >0 trades first-event latency
        # for fuller (more coalesced) batches under bursty streams
        self.watch_batch_s = watch_batch_s
        # server-side kind filter: a consumer that only reads nodes/pods
        # must not pay the encode/decode of every Event record the
        # cluster emits. None = the full stream.
        self.watch_kinds = tuple(watch_kinds) if watch_kinds else None
        self._watchers: list = []
        self._batch_watchers: list = []
        self._relist_listeners: list = []
        self._watch_thread = None
        self._stop = threading.Event()
        # racer: single-writer -- threading.local: each thread writes
        # only its own slot by construction
        self._local = threading.local()  # per-thread keep-alive connection
        self._conn_lock = threading.Lock()
        self._conns: set = set()  # every live connection, for close()
        self._stream_conns: set = set()  # live framed conns, for close()
        # transport-level retries performed; bumped under _conn_lock —
        # every thread with a keep-alive connection retries through here
        self.retry_count = 0
        # 429/REJECT flow-control answers honored (the retry deferred
        # by the server-advised retry_after_s); same guard discipline
        self.throttled_count = 0
        self.watch_errors = 0  # failed watch polls survived
        self.relist_count = 0  # watch resume gaps that forced a relist

    def _roundtrip(self, method: str, path: str, data, timeout: float):
        """One request over this thread's keep-alive connection; returns
        ``(status, body bytes)``. Any transport fault closes the cached
        connection so the next attempt reconnects cleanly — this is the
        single seam tests use to inject transport failures."""
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self._stop.is_set():
                # a closed client must not quietly re-dial: the watch
                # thread caught mid-poll used to open a FRESH connection
                # after close() and long-poll the server for up to 30
                # more seconds past the client's lifetime (the socket
                # leak the resource-lifecycle work was built to end)
                raise ConnectionError("client is closed")
            split = urllib.parse.urlsplit(self.base_url)
            cls = http.client.HTTPSConnection if split.scheme == "https" \
                else http.client.HTTPConnection
            conn = cls(split.hostname, split.port, timeout=timeout)
            self._local.conn = conn
            with self._conn_lock:
                self._conns.add(conn)
        try:
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            else:
                conn.timeout = timeout
                conn.connect()
                # small JSON requests must not sit out a Nagle window
                conn.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
                conn.sock.settimeout(timeout)
            headers = {"Content-Type": "application/json"}
            trace_ctx = obs.header_value()
            if trace_ctx is not None:
                # carry the caller's span context across the hop: the
                # server parents its arbiter/WAL spans under it
                headers[obs.TRACE_HEADER] = trace_ctx
            conn.request(method, path, body=data, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            # body bytes only (HTTP headers uncounted — the json wire's
            # real framing overhead is larger than this shows)
            label = self.transport_label or stream.WIRE_JSON
            metrics.TRANSPORT_BYTES.labels(label, "tx").inc(
                len(data) if data else 0)
            metrics.TRANSPORT_BYTES.labels(label, "rx").inc(
                len(payload))
            return resp.status, payload
        except Exception:
            self._local.conn = None
            with self._conn_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            raise

    def _stream_roundtrip(self, method: str, path: str, body, timeout):
        """Stream-wire twin of :meth:`_roundtrip`: one framed request on
        this thread's persistent stream connection; returns ``(status,
        decoded body)``. Any transport or framing fault drops the cached
        connection so the next attempt reconnects cleanly — the fault-
        injection seam for the stream wire, like ``_roundtrip`` for
        JSON."""
        conn = getattr(self._local, "stream", None)
        if conn is None or conn.closed:
            if self._stop.is_set():
                raise ConnectionError("client is closed")
            conn = stream.StreamConn.connect(
                self.base_url, timeout, label=self.transport_label)
            self._local.stream = conn
            with self._conn_lock:
                self._stream_conns.add(conn)
        try:
            return conn.request(method, path, body, timeout,
                                trace=obs.header_value())
        except BaseException:
            self._local.stream = None
            with self._conn_lock:
                self._stream_conns.discard(conn)
            conn.close()
            raise

    def _wire_roundtrip(self, method: str, path: str, body, timeout):
        """One round trip over whichever wire is in effect; returns
        ``(status, decoded document)``. An upgrade answered with plain
        HTTP negotiates this client down to the JSON wire — once,
        permanently, and transparently to the caller."""
        if self.wire == stream.WIRE_STREAM:
            try:
                return self._stream_roundtrip(method, path, body, timeout)
            except stream.StreamUnsupported:
                logging.getLogger(__name__).info(
                    "server at %s has no stream wire; negotiated down "
                    "to json", self.base_url)
                # racer: single-writer -- one-way latch: every racing
                # writer stores the same constant, atomically under the GIL
                self.wire = stream.WIRE_JSON
        data = json.dumps(body).encode() if body is not None else None
        status, payload = self._roundtrip(method, path, data, timeout)
        text = payload.decode()
        try:
            doc = json.loads(text) if text else {}
        except ValueError:
            doc = {"error": text}
        return status, doc

    def _count_retry(self) -> None:
        """Count one transport retry, guarded: every thread with a
        keep-alive connection funnels through this counter, and an
        unguarded ``+=`` from N concurrent retriers loses updates (the
        racer rule's first true positive in this file)."""
        probe("httpapi.count_retry")
        with self._conn_lock:
            self.retry_count += 1

    def _req(self, method: str, path: str, body=None, timeout=None):
        """One API round trip. Idempotent verbs retry transient transport
        failures (connection reset, refused, timeout, torn/corrupt
        frames) with capped exponential backoff + jitter; a *response* —
        any status, either wire — is the server speaking and is never
        blind-retried here. The one exception is flow control: a 429 /
        REJECT carries the server's advised ``retry_after_s``, and the
        idempotent-retry policy HONORS it (the advised delay replaces
        the computed backoff) before resending; POSTs stay single-shot
        and surface the typed :class:`TooManyRequests` to the caller."""
        attempts = self.RETRY_ATTEMPTS \
            if method in self.IDEMPOTENT_METHODS else 1
        for attempt in range(attempts):
            try:
                status, doc = self._wire_roundtrip(
                    method, path, body, timeout or self.timeout)
            except (urllib.error.URLError, http.client.HTTPException,
                    ConnectionError, TimeoutError, OSError):
                if attempt + 1 >= attempts:
                    raise
                self._count_retry()
                backoff = min(self.RETRY_CAP_S,
                              self.RETRY_BASE_S * 2 ** attempt)
                # jitter so a fleet of clients doesn't resend in lockstep
                self._stop.wait(backoff * (0.5 + random.random() / 2.0))
                continue
            if status < 400:
                return doc if isinstance(doc, dict) else {}
            if status == 429:
                if attempt + 1 < attempts:
                    advised = float(doc.get("retry_after_s") or 0.0) \
                        if isinstance(doc, dict) else 0.0
                    self._count_throttle()
                    backoff = min(self.RETRY_CAP_S,
                                  self.RETRY_BASE_S * 2 ** attempt)
                    # server-advised backoff wins over the computed
                    # one: the front door knows its queue depth, we
                    # don't (a fleet resending early is exactly the
                    # flood APF sheds). Jitter spreads resends ABOVE
                    # the advised floor — resending early would defeat
                    # the advice.
                    delay = advised if advised > 0 else backoff
                    self._stop.wait(delay *
                                    (1.0 + random.random() / 4.0))
                    continue
                raise self._server_error(TooManyRequests, doc)
            if status == 403:
                raise self._server_error(QuotaExceeded, doc)
            if status == 404:
                if method == "DELETE" and attempt > 0:
                    # Our earlier attempt may have landed and lost its
                    # reply: this 404 is "already deleted", not "was
                    # never there". Report success so a caller that
                    # distinguishes its own delete from an external
                    # one (NodeLifecycle eviction) is not tricked
                    # into reading a clean not-found — the transport
                    # retry must not hide the ambiguity it created.
                    return {}
                raise self._server_error(NotFound, doc)
            if status == 409:
                raise self._server_error(Conflict, doc)
            detail = doc.get("error", doc) if isinstance(doc, dict) else doc
            raise RuntimeError(f"HTTP {status}: {detail}")

    @staticmethod
    def _server_error(cls, doc):
        """Reconstruct a typed server error from the error document —
        per-pod detail (the binder's conflict handling needs the same
        ``per_pod`` the in-memory server raises with) and the front
        door's advised ``retry_after_s`` (which the retry policy
        honors) both survive the wire."""
        per_pod = None
        text = str(doc)
        retry_after = None
        if isinstance(doc, dict):
            per_pod = doc.get("per_pod")
            text = doc.get("error", text)
            retry_after = doc.get("retry_after_s")
        if cls is TooManyRequests:
            return cls(text, per_pod=per_pod,
                       retry_after_s=float(retry_after or 0.0))
        return cls(text, per_pod=per_pod)

    def _count_throttle(self) -> None:
        """Count one honored flow-control rejection, guarded like
        ``_count_retry`` (any thread's request can be shed)."""
        with self._conn_lock:
            self.throttled_count += 1

    def forward(self, method: str, path: str, body=None, timeout=None):
        """Hop-transparent round trip: returns the raw ``(status,
        document)`` pair for ANY status. The watch-cache proxy forwards
        through this instead of :meth:`_req` because a hop must not act
        like an endpoint: typed errors are not raised here (the proxy
        re-raises them itself so its OWN transport re-maps them to the
        identical status + error body), and an upstream 429's advised
        ``retry_after_s`` passes through unshortened instead of
        disciplining the proxy's retry loop. Transport faults retry
        exactly like ``_req`` — idempotent verbs only."""
        attempts = self.RETRY_ATTEMPTS \
            if method in self.IDEMPOTENT_METHODS else 1
        for attempt in range(attempts):
            try:
                return self._wire_roundtrip(
                    method, path, body, timeout or self.timeout)
            except (urllib.error.URLError, http.client.HTTPException,
                    ConnectionError, TimeoutError, OSError):
                if attempt + 1 >= attempts:
                    raise
                self._count_retry()
                backoff = min(self.RETRY_CAP_S,
                              self.RETRY_BASE_S * 2 ** attempt)
                self._stop.wait(backoff * (0.5 + random.random() / 2.0))

    # -- node/pod surface ---------------------------------------------------

    def create_node(self, node):
        return self._req("POST", "/nodes", node)

    def get_node(self, name):
        return self._req("GET", f"/nodes/{name}")

    def list_nodes(self):
        return self._req("GET", "/nodes")["items"]

    def patch_node_metadata(self, name, patch):
        return self._req("PATCH", f"/nodes/{name}/metadata", patch)

    def delete_node(self, name):
        return self._req("DELETE", f"/nodes/{name}")

    def create_pod(self, pod):
        return self._req("POST", "/pods", pod)

    def get_pod(self, name):
        return self._req("GET", f"/pods/{name}")

    def list_pods(self, node_name=None, phase=None, bound=False):
        q = [p for p in (f"node={node_name}" if node_name else "",
                         f"phase={phase}" if phase else "",
                         "bound=1" if bound else "") if p]
        path = "/pods" + ("?" + "&".join(q) if q else "")
        return self._req("GET", path)["items"]

    def update_pod_annotations(self, name, annotations):
        return self._req("PUT", f"/pods/{name}/annotations", annotations)

    def update_pod_annotations_many(self, annotations):
        """{pod name -> annotations} replaced in ONE request (and one
        server lock pass) — the gang paths' N-member stamp."""
        return self._req("PUT", "/podannotations", annotations)

    def bind_pod(self, name, node_name):
        return self._req("POST", f"/pods/{name}/bind", {"node": node_name})

    def bind_many(self, bindings, annotations):
        return self._req("POST", "/bindmany",
                         {"bindings": bindings, "annotations": annotations})

    def delete_pod(self, name):
        return self._req("DELETE", f"/pods/{name}")

    def create_pdb(self, pdb):
        return self._req("POST", "/pdbs", pdb)

    def list_pdbs(self):
        return self._req("GET", "/pdbs")["items"]

    def delete_pdb(self, name):
        return self._req("DELETE", f"/pdbs/{name}")

    # -- selector owners (SelectorSpreadPriority listers) --------------------

    def create_service(self, svc):
        return self._req("POST", "/services", svc)

    def list_services(self):
        return self._req("GET", "/services")["items"]

    def delete_service(self, name):
        return self._req("DELETE", f"/services/{name}")

    def create_rc(self, rc):
        return self._req("POST", "/rcs", rc)

    def list_rcs(self):
        return self._req("GET", "/rcs")["items"]

    def delete_rc(self, name):
        return self._req("DELETE", f"/rcs/{name}")

    def create_rs(self, rs):
        return self._req("POST", "/rss", rs)

    def list_rss(self):
        return self._req("GET", "/rss")["items"]

    def delete_rs(self, name):
        return self._req("DELETE", f"/rss/{name}")

    def create_statefulset(self, ss):
        return self._req("POST", "/statefulsets", ss)

    def list_statefulsets(self):
        return self._req("GET", "/statefulsets")["items"]

    def delete_statefulset(self, name):
        return self._req("DELETE", f"/statefulsets/{name}")

    # -- persistent volumes / claims ----------------------------------------

    def create_pvc(self, pvc):
        return self._req("POST", "/pvcs", pvc)

    def get_pvc(self, name):
        return self._req("GET", f"/pvcs/{name}")

    def list_pvcs(self):
        return self._req("GET", "/pvcs")["items"]

    def delete_pvc(self, name):
        return self._req("DELETE", f"/pvcs/{name}")

    def create_pv(self, pv):
        return self._req("POST", "/pvs", pv)

    def get_pv(self, name):
        return self._req("GET", f"/pvs/{name}")

    def list_pvs(self):
        return self._req("GET", "/pvs")["items"]

    def delete_pv(self, name):
        return self._req("DELETE", f"/pvs/{name}")

    def bind_volume(self, pv_name, claim_name):
        return self._req("POST", "/bindvolume",
                         {"pv": pv_name, "pvc": claim_name})

    # -- tenant quotas -------------------------------------------------------

    def list_quotas(self):
        """{tenant: quota spec + live chips_created} — the admin view
        of the tenant ledger."""
        return self._req("GET", "/quotas")["items"]

    def set_quota(self, tenant, spec):
        """Configure a tenant's fair-share ``weight`` and/or create-time
        ``hard_chips`` cap."""
        return self._req("PUT", f"/quotas/{tenant}", spec)

    def delete_quota(self, tenant):
        return self._req("DELETE", f"/quotas/{tenant}")

    def record_event(self, kind, name, event_type, reason, message):
        return self._req("POST", "/events",
                         {"kind": kind, "name": name, "type": event_type,
                          "reason": reason, "message": message})

    def record_events(self, events):
        """Batched event recording: one POST for the whole list."""
        return self._req("POST", "/events", list(events))

    def list_events(self, involved_name=None):
        path = "/events" + (f"?involved={involved_name}"
                            if involved_name else "")
        return self._req("GET", path)["items"]

    def acquire_lease(self, name, holder, ttl_s):
        try:
            self._req("POST", f"/leases/{name}",
                      {"holder": holder, "ttl": ttl_s})
            return True
        except Conflict:
            return False

    def lease_holder(self, name):
        """Current holder of a lease, or None when vacant/expired — the
        shard coordinator's work-stealing probe."""
        return self._req("GET", f"/leases/{name}").get("holder")

    def release_lease(self, name, holder):
        """Drop a lease this holder owns (clean handoff on shutdown)."""
        self._req("DELETE", f"/leases/{name}?holder={holder}")
        return True

    # -- watch --------------------------------------------------------------

    def add_watcher(self, fn):
        self._watchers.append(fn)
        self._ensure_watch_thread()

    def add_relist_listener(self, fn):
        """Register ``fn()`` called when the watch stream's resume
        window is lost (apiserver restarted without durable state, or
        our cursor predates its snapshot): the consumer must re-list
        and reconcile — resuming deltas alone would silently skip
        whatever the gap held."""
        self._relist_listeners.append(fn)

    def add_batch_watcher(self, fn):
        """Register ``fn(events)`` called once per poll with the whole
        batch (``[(kind, event, obj), ...]``) — the consumer applies it
        under ONE cache lock instead of a lock round-trip per event."""
        self._batch_watchers.append(fn)
        self._ensure_watch_thread()

    def _ensure_watch_thread(self):
        if self._watch_thread is None:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True, name="api-watch")
            self._watch_thread.start()

    def _watch_loop(self):
        """Informer loop. MUST outlive transient transport errors: the
        consumers behind it (scheduler cache, queue wake-ups) have no
        other event source, so a watch thread dying silently strands the
        whole control loop. Failures back off exponentially (capped),
        are counted in ``watch_errors``, logged once per failure streak,
        and every recovery resumes from the last seen sequence number —
        no events skipped, none replayed (the server may COALESCE events
        per object, but never reorders or rewinds an object's history).

        Two wires, one cursor contract: the JSON wire long-polls
        ``/watch?since=seq``; the stream wire holds a subscription on a
        framed connection and the server PUSHES each coalesced batch.
        The wire can flip stream->json mid-loop (negotiated fallback) —
        the cursor survives the flip."""
        obs.register_thread("informer")
        log = logging.getLogger(__name__)
        st = {"seq": 0, "epoch": None, "failures": 0}
        while not self._stop.is_set():
            if self.wire == stream.WIRE_STREAM:
                self._watch_stream_session(st, log)
            else:
                self._watch_json_poll(st, log)

    def _watch_failed(self, st: dict, log, what: str):
        self.watch_errors += 1
        st["failures"] += 1
        if st["failures"] == 1:
            log.warning("%s failed; retrying from seq %d", what,
                        st["seq"], exc_info=True)
        self._stop.wait(min(5.0, 0.2 * 2 ** min(st["failures"] - 1, 5)))

    def _watch_json_poll(self, st: dict, log):
        """One long-poll round trip on the JSON wire."""
        path = f"/watch?since={st['seq']}&timeout=5"
        if self.watch_batch_s > 0:
            path += f"&batch={self.watch_batch_s}"
        if self.watch_kinds:
            path += "&kinds=" + ",".join(self.watch_kinds)
        try:
            out = self._req("GET", path, timeout=30.0)
        except Exception:
            self._watch_failed(st, log, "watch poll")
            return
        if st["failures"]:
            log.info("watch recovered after %d failed polls; "
                     "resuming from seq %d", st["failures"], st["seq"])
            st["failures"] = 0
        self._apply_watch_out(st, out, log)

    def _watch_stream_session(self, st: dict, log):
        """One stream-wire watch session: subscribe at the cursor, then
        consume server pushes until the connection dies (or the server
        turns out not to speak the stream wire at all — negotiated
        fallback to the JSON long-poll, same cursor)."""
        conn = None
        try:
            conn = stream.StreamConn.connect(
                self.base_url, 10.0, label=self.transport_label)
            with self._conn_lock:
                if self._stop.is_set():
                    # close() already swept the connection set; a conn
                    # registered after that sweep would outlive the
                    # client — drop it instead (the finally closes it)
                    return
                self._stream_conns.add(conn)
            ack = conn.subscribe(st["seq"], self.watch_kinds,
                                 self.watch_batch_s, timeout=10.0)
            if st["failures"]:
                log.info("watch recovered after %d failed attempts; "
                         "resuming from seq %d", st["failures"],
                         st["seq"])
                st["failures"] = 0
            # the ack only carries the server's head + epoch — it must
            # never ADVANCE the cursor (pushes covering the gap are
            # already on their way), but a regressed head or a changed
            # epoch is still a restart to detect. When the ack DOES
            # detect one, this session's server-side subscription was
            # registered at the stale pre-adoption cursor — drop the
            # connection and resubscribe at the adopted cursor, so the
            # server's own relist push cannot fire the listeners a
            # second time (the long-poll wire relists exactly once).
            if self._apply_watch_out(
                    st, {"events": [], "seq": ack.get("seq"),
                         "epoch": ack.get("epoch")}, log, advance=False):
                return
            while not self._stop.is_set():
                out = conn.read_push(timeout=30.0)
                if out is None:  # liveness ping
                    continue
                st["failures"] = 0
                ts = out.get("ts") or 0.0
                if ts:
                    # cross-process wall-clock stamp (like the heartbeat
                    # annotation): push lag from server encode to here
                    metrics.WATCH_PUSH_LAG_MS.observe(
                        max(0.0, (time.time() - ts) * 1e3))  # analysis: disable=monotonic-time -- cross-process stamp comparison, never liveness
                self._apply_watch_out(st, out, log)
        except stream.StreamUnsupported:
            log.info("server at %s has no stream wire; watch falls "
                     "back to the JSON long-poll", self.base_url)
            self.wire = stream.WIRE_JSON
        except Exception:
            if self._stop.is_set():
                return
            self._watch_failed(st, log, "watch stream")
        finally:
            if conn is not None:
                conn.close()
                with self._conn_lock:
                    self._stream_conns.discard(conn)

    def _apply_watch_out(self, st: dict, out: dict, log,
                         advance: bool = True) -> bool:
        """Shared cursor + delivery contract for both wires: relist /
        epoch-change / seq-regress handling, then batch delivery. With
        ``advance=False`` only the restart checks run (a stream
        subscribe ack: deliveries for the gap are in flight, adopting
        the server head would skip them). Returns True when the
        restart branch ran (cursor adopted, relist fired if due) —
        the stream session uses that to resubscribe at the adopted
        cursor."""
        seq = st["seq"]
        srv_seq = int(out.get("seq", seq) or 0)
        srv_epoch = out.get("epoch")
        stream_moved = (st["epoch"] is not None and srv_epoch is not None
                        and srv_epoch != st["epoch"])
        if srv_epoch is not None:
            st["epoch"] = srv_epoch
        if out.get("relist") or srv_seq < seq or stream_moved:
            # The server told us our cursor is unreplayable (relist
            # flag), its sequence space moved BACKWARD, or its
            # stream EPOCH changed — a restart without durable
            # state, including the case where the new life's
            # sequence numbers already overlap our old cursor (a
            # bare seq comparison cannot see that gap). Either way
            # the delta stream has a hole: adopt the server's cursor
            # and make the consumers re-list, never resume silently
            # stale. A FRESH client (cursor 0) has seen nothing and
            # so missed nothing — its consumers' own initial sync
            # covers the history a compacted WAL can no longer
            # replay; firing a relist there would just double the
            # startup LIST.
            if seq > 0:
                self.relist_count += 1
                log.warning("watch resume window lost (client seq "
                            "%d, server seq %d); relisting", seq,
                            srv_seq)
                st["seq"] = srv_seq
                for fn in list(self._relist_listeners):
                    try:
                        fn()
                    except Exception:
                        log.warning("relist listener %r failed", fn,
                                    exc_info=True)
            else:
                st["seq"] = srv_seq
            return True
        events = out.get("events", [])
        if events:
            metrics.WATCH_BATCH_SIZE.set(len(events))
            folded = int(out.get("coalesced", 0) or 0)
            if folded:
                metrics.WATCH_COALESCED.inc(folded)
            batch = []
            for ev_seq, kind, event, obj in events:
                if advance:
                    st["seq"] = max(st["seq"], ev_seq)
                batch.append((kind, event, obj))
            for bfn in list(self._batch_watchers):
                try:
                    bfn(batch)
                except Exception:
                    log.warning("batch watch consumer %r failed on a "
                                "%d-event batch", bfn, len(batch),
                                exc_info=True)
            for kind, event, obj in batch:
                for fn in list(self._watchers):
                    try:
                        fn(kind, event, obj)
                    except Exception:
                        # a bad consumer must not kill the informer,
                        # but a consumer that throws on every event is
                        # a dead scheduler cache — it must be visible
                        log.warning("watch consumer %r failed on %s "
                                    "%s event", fn, kind, event,
                                    exc_info=True)
        if advance:
            st["seq"] = max(st["seq"], srv_seq)
        return False

    def close(self):
        self._stop.set()
        # tear down every thread's keep-alive connection — the old
        # per-request transport released sockets implicitly; this one
        # must not leak them past the client's lifetime. A thread caught
        # mid-request sees a connection error, which is what close means.
        with self._conn_lock:
            conns = list(self._conns)
            self._conns.clear()
            sconns = list(self._stream_conns)
            self._stream_conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        for sconn in sconns:
            sconn.close()
        # the watch thread's sockets are dead and _roundtrip refuses new
        # ones, so the loop exits promptly: join it so close() returns a
        # client with NO live threads (the per-test leak guard's
        # contract, and what a 'closed' client should mean)
        watcher = self._watch_thread
        if watcher is not None and watcher is not threading.current_thread():
            watcher.join(timeout=5.0)

"""A thread-safe in-memory stand-in for the Kubernetes API server surface
this framework uses: node/pod objects (plain JSON-shaped dicts), metadata
patching, binding, and change notification.

Only the operations the reference performs are modeled
(`kubeinterface.go:145-193`, scheduler bind at `scheduler.go:405-417`):
get/patch node metadata, get/update pod annotations, bind.
"""

from __future__ import annotations

import copy
import json
import threading
import time

from kubegpu_tpu import obs
from kubegpu_tpu.analysis.explore import probe
from kubegpu_tpu.cluster import apf
from kubegpu_tpu.cluster.lease import LeaseTable
from kubegpu_tpu.core import codec, grammar

# Span identity for the arbiter's trace rows: whichever process hosts
# this store (a dedicated apiserver binary or an in-process simulate),
# its commit/refusal spans must be tellable apart from scheduler spans.
_OBS_PROC = "apiserver"

# The gang process contract's annotation key (scheduler/gang.py writes
# it). Spelled out here rather than imported: the cluster layer must not
# depend on the scheduler package — the arbiter only reads the wire shape.
_GANG_PROCESS_ANNOTATION = "pod.alpha/GangProcess"


class NotFound(KeyError):
    """Object missing. Batched verbs (``bind_many``,
    ``update_pod_annotations_many``) attach ``per_pod`` — {pod name ->
    reason} — so a client can tell WHICH pods failed instead of
    degrading the whole batch."""

    def __init__(self, message: str = "", per_pod: dict | None = None):
        super().__init__(message)
        self.per_pod = dict(per_pod or {})

    def __str__(self) -> str:
        # KeyError's str() repr-quotes its message, which re-quotes on
        # every reconstruct -> re-serialize pass — through the
        # watch-cache proxy the error text must round-trip verbatim, so
        # a hop is invisible in the body a client sees
        return str(self.args[0]) if self.args else ""


class Conflict(RuntimeError):
    """Optimistic-concurrency refusal: the write would contradict
    committed state (pod bound elsewhere, chip already allocated to
    another bound pod, coordinator port promised to another gang).
    ``per_pod`` carries the per-pod reasons for batched verbs — the
    binder uses it to forget+requeue exactly the losers and commit the
    rest, and to distinguish this definitive server answer from a
    transient transport failure (which retries in place)."""

    def __init__(self, message: str = "", per_pod: dict | None = None):
        super().__init__(message)
        self.per_pod = dict(per_pod or {})


class QuotaExceeded(RuntimeError):
    """A tenant is over its chip quota. Two raisers, one type: the
    apiserver's create-time admission when a configured HARD cap
    (``set_quota(tenant, hard_chips=...)``) would be exceeded — mapped
    to HTTP 403 on both wires like real Kubernetes ResourceQuota — and
    the scheduler's dominant-resource fair-share gate at pod-pop time
    (``scheduler/quota.py``), where it is the typed unschedulable
    reason a parked pod shows in ``/debug/pod/<name>``."""

    def __init__(self, message: str = "", per_pod: dict | None = None):
        super().__init__(message)
        self.per_pod = dict(per_pod or {})


def _pod_claims(annotations: dict | None) -> tuple:
    """What a pod's annotations pin on its node: ``(chip prefixes,
    coordinator claim | None)``. Chip prefixes come from the device
    allocation's ``allocatefrom`` paths ((node, prefix) identifies a
    physical chip — same keying as the gang preemption planner); the
    coordinator claim is ``(node, port, gang id)`` from the gang process
    contract. Unparseable annotations claim nothing — the arbiter must
    never turn a malformed pod into a refused bind."""
    chips: set = set()
    coord = None
    ann = annotations or {}
    raw = ann.get(codec.POD_ANNOTATION_KEY)
    if raw:
        try:
            dev = json.loads(raw)
        except (TypeError, ValueError):
            dev = None
        if isinstance(dev, dict):
            for section in ("initcontainer", "runningcontainer"):
                for cont in (dev.get(section) or {}).values():
                    if not isinstance(cont, dict):
                        continue
                    for path in (cont.get("allocatefrom") or {}).values():
                        prefix = grammar.chip_prefix_from_path(str(path))
                        if prefix is not None:
                            chips.add(prefix)
    raw = ann.get(_GANG_PROCESS_ANNOTATION)
    if raw:
        try:
            gp = json.loads(raw)
            coord = (str(gp["coordinator_node"]),
                     int(gp["coordinator_port"]), int(gp["gang"]))
        except (TypeError, ValueError, KeyError):
            coord = None
    return chips, coord


def _merge(dst: dict, patch: dict) -> None:
    """Strategic-merge-patch for the metadata shapes we carry: dicts merge
    recursively, everything else replaces."""
    for key, val in patch.items():
        if isinstance(val, dict) and isinstance(dst.get(key), dict):
            _merge(dst[key], val)
        else:
            dst[key] = copy.deepcopy(val)


class InMemoryAPIServer:
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: dict = {}
        self._pods: dict = {}
        self._pdbs: dict = {}
        self._pvcs: dict = {}
        self._pvs: dict = {}
        # selector owners for SelectorSpreadPriority
        # (`selector_spreading.go`: services, RCs, RSs, StatefulSets)
        self._owners: dict = {k: {} for k in
                              ("service", "rc", "rs", "statefulset")}
        # insertion-ordered (kind, name, reason, message) -> event; the
        # key IS the dedup identity, so record_event is O(1) not a scan
        self._events: dict = {}
        self._watchers: list = []
        # Secondary pod indexes, maintained under self._lock by every pod
        # mutator (the same discipline as _notify_locked): lifecycle
        # eviction, gang lookup, and preemption's victim scan read
        # pods-by-node / bound / by-phase slices instead of sweeping
        # every pod in the cluster.
        self._pods_by_node: dict = {}   # node name -> {pod names}
        self._pods_by_phase: dict = {}  # status.phase -> {pod names}
        # Optimistic-concurrency claim indexes (multi-scheduler HA): what
        # each BOUND pod's annotations pin, maintained by the same
        # index/deindex discipline as the pod indexes above. bind_pod /
        # bind_many arbitrate against these — a bind that would
        # oversubscribe a chip or re-bind a taken coordinator port
        # returns Conflict with per-pod detail, which is what lets N
        # scheduler replicas commit through one shared store safely.
        self._chip_claims: dict = {}   # (node, chip prefix) -> pod name
        self._coord_claims: dict = {}  # (node, port) -> [gang id, {pods}]
        # Tenant quota config (tenant -> {"weight", "hard_chips"}) and
        # the incremental created-chips ledger admission checks against:
        # per-pod entries so bind-time re-indexing and WAL replay stay
        # idempotent, maintained by the same index/deindex discipline.
        self._quotas: dict = {}
        self._tenant_chips: dict = {}      # tenant -> chips created
        self._pod_tenant_chips: dict = {}  # pod name -> (tenant, chips)
        # Leader-election / shard-ownership leases, served uniformly by
        # every client surface (in-process here, HTTP via httpapi).
        self._leases = LeaseTable()

    # ---- leases ------------------------------------------------------------

    def acquire_lease(self, name: str, holder: str, ttl_s: float) -> bool:
        return self._leases.acquire(name, holder, ttl_s)

    def lease_holder(self, name: str):
        return self._leases.holder(name)

    def release_lease(self, name: str, holder: str) -> bool:
        return self._leases.release(name, holder)

    MAX_EVENTS = 5000

    # ---- tenant quotas -----------------------------------------------------

    def set_quota(self, tenant: str, spec: dict) -> dict:
        """Configure one tenant's quota: ``weight`` (fair-share weight
        the scheduler-side DRF gate consumes) and/or ``hard_chips`` (a
        create-time admission cap this server enforces itself)."""
        out = {}
        if "weight" in spec and spec["weight"] is not None:
            out["weight"] = float(spec["weight"])
        if "hard_chips" in spec and spec["hard_chips"] is not None:
            out["hard_chips"] = int(spec["hard_chips"])
        with self._lock:
            self._quotas[tenant] = out
            self._notify_locked("quota", "modified",
                                {"metadata": {"name": tenant},
                                 "spec": dict(out)})
            return dict(out)

    def delete_quota(self, tenant: str) -> None:
        with self._lock:
            if tenant not in self._quotas:
                raise NotFound(f"quota {tenant}")
            spec = self._quotas.pop(tenant)
            self._notify_locked("quota", "deleted",
                                {"metadata": {"name": tenant},
                                 "spec": dict(spec)})

    def list_quotas(self) -> dict:
        """{tenant: quota spec + live ``chips_created`` usage} — the
        admin/debug view of the tenant ledger."""
        with self._lock:
            tenants = set(self._quotas) | set(self._tenant_chips)
            return {t: {**(self._quotas.get(t) or {}),
                        "chips_created":
                            round(self._tenant_chips.get(t, 0.0), 3)}
                    for t in sorted(tenants)}

    def _check_hard_quota_locked(self, pod: dict) -> None:
        """Create-time admission: refuse a pod that would push its
        tenant past a configured hard chip cap (HTTP 403 on the wire,
        like real ResourceQuota). No cap configured = no gate; WAL
        replay bypasses this path entirely (restore_object)."""
        tenant = apf.tenant_of_pod(pod)
        if tenant is None:
            return
        cap = (self._quotas.get(tenant) or {}).get("hard_chips")
        if cap is None:
            return
        want = apf.pod_chip_request(pod)
        used = self._tenant_chips.get(tenant, 0.0)
        if used + want > cap:
            raise QuotaExceeded(
                f"tenant {tenant!r} over hard chip cap: "
                f"{used:.0f} created + {want} requested > {cap}")

    def _charge_tenant_locked(self, pod: dict) -> None:
        name = pod["metadata"]["name"]
        if name in self._pod_tenant_chips:
            return
        tenant = apf.tenant_of_pod(pod)
        if tenant is None:
            return
        chips = float(apf.pod_chip_request(pod))
        self._pod_tenant_chips[name] = (tenant, chips)
        self._tenant_chips[tenant] = \
            self._tenant_chips.get(tenant, 0.0) + chips

    def _discharge_tenant_locked(self, pod: dict) -> None:
        entry = self._pod_tenant_chips.pop(pod["metadata"]["name"], None)
        if entry is None:
            return
        tenant, chips = entry
        left = self._tenant_chips.get(tenant, 0.0) - chips
        if left > 1e-9:
            self._tenant_chips[tenant] = left
        else:
            self._tenant_chips.pop(tenant, None)

    # ---- nodes -------------------------------------------------------------

    def create_node(self, node: dict) -> dict:
        with self._lock:
            name = node["metadata"]["name"]
            self._nodes[name] = copy.deepcopy(node)
            self._notify_locked("node", "added", self._nodes[name])
            return copy.deepcopy(self._nodes[name])

    def get_node(self, name: str) -> dict:
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name}")
            return copy.deepcopy(self._nodes[name])

    def list_nodes(self) -> list:
        with self._lock:
            return [copy.deepcopy(n) for _, n in sorted(self._nodes.items())]

    def patch_node_metadata(self, name: str, metadata_patch: dict) -> dict:
        """Strategic-merge-patch of node metadata
        (`kubeinterface.go:145-158`). A patch that changes nothing
        delivers NO watch event: every node event is an invalidation
        source for the scheduler's fit memo (and requeues unschedulable
        pods), so an idempotent re-advertise must not masquerade as a
        node change."""
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name}")
            meta = self._nodes[name].setdefault("metadata", {})
            before = copy.deepcopy(meta)
            _merge(meta, metadata_patch)
            if meta != before:
                self._notify_locked("node", "modified", self._nodes[name])
            return copy.deepcopy(self._nodes[name])

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is None:
                # raise like the HTTP transport's 404 and real Kubernetes:
                # a caller distinguishing "I deleted it" from "it was
                # already gone" (eviction, preemption) needs the signal
                raise NotFound(f"node {name}")
            self._notify_locked("node", "deleted", node)

    # ---- pods --------------------------------------------------------------

    def _index_pod_locked(self, pod: dict) -> None:
        # Always called with self._lock held, right after a pod mutation:
        # the index entry must be atomic with the object state it mirrors.
        name = pod["metadata"]["name"]
        node = (pod.get("spec") or {}).get("nodeName")
        phase = (pod.get("status") or {}).get("phase")
        self._charge_tenant_locked(pod)
        if node:
            self._pods_by_node.setdefault(node, set()).add(name)
            chips, coord = _pod_claims(
                (pod.get("metadata") or {}).get("annotations"))
            for prefix in chips:
                self._chip_claims[(node, prefix)] = name
            if coord is not None:
                cnode, port, gang = coord
                entry = self._coord_claims.setdefault((cnode, port),
                                                      [gang, set()])
                entry[1].add(name)
        if phase:
            self._pods_by_phase.setdefault(phase, set()).add(name)

    def _deindex_pod_locked(self, pod: dict) -> None:
        # Always called with self._lock held, BEFORE a mutation that may
        # move the pod between index buckets (bind, delete).
        name = pod["metadata"]["name"]
        node = (pod.get("spec") or {}).get("nodeName")
        phase = (pod.get("status") or {}).get("phase")
        self._discharge_tenant_locked(pod)
        if node:
            bucket = self._pods_by_node.get(node)
            if bucket is not None:
                bucket.discard(name)
                if not bucket:
                    del self._pods_by_node[node]
            chips, coord = _pod_claims(
                (pod.get("metadata") or {}).get("annotations"))
            for prefix in chips:
                if self._chip_claims.get((node, prefix)) == name:
                    del self._chip_claims[(node, prefix)]
            if coord is not None:
                cnode, port, _gang = coord
                entry = self._coord_claims.get((cnode, port))
                if entry is not None:
                    entry[1].discard(name)
                    if not entry[1]:
                        del self._coord_claims[(cnode, port)]
        if phase:
            bucket = self._pods_by_phase.get(phase)
            if bucket is not None:
                bucket.discard(name)
                if not bucket:
                    del self._pods_by_phase[phase]

    def _bind_conflicts_locked(self, bindings: dict,
                               annotations: dict) -> dict:
        # Always called with self._lock held. The optimistic-concurrency
        # arbiter: per-pod reasons a proposed bind set must be refused —
        # pod already bound elsewhere, a chip already allocated to
        # another BOUND pod (or claimed twice within this batch), or a
        # coordinator port promised to a different gang. A pod re-bound
        # to its own node is a no-op (retries converge) and is never a
        # conflict with itself.
        per_pod: dict = {}
        batch_chips: dict = {}   # (node, prefix) -> pod name in this batch
        batch_coords: dict = {}  # (node, port) -> gang id in this batch
        for name in sorted(bindings):
            node_name = bindings[name]
            pod = self._pods.get(name)
            if pod is None:
                continue  # caller raises NotFound with its own detail
            bound = (pod.get("spec") or {}).get("nodeName")
            if bound and bound != node_name:
                per_pod[name] = f"already bound to {bound}"
                continue
            ann = annotations.get(name)
            if bound:
                # Re-binding a bound pod converges ONLY when it carries
                # the identical allocation (a lost-reply resend). A
                # competing replica's DIFFERENT allocation for the same
                # pod is a conflicting commit — accepting it would
                # silently swap the pod's chips under every other
                # replica's accounting.
                cur = (pod.get("metadata") or {}).get("annotations") or {}
                if ann is not None and any(
                        (ann or {}).get(key) != cur.get(key)
                        for key in (codec.POD_ANNOTATION_KEY,
                                    _GANG_PROCESS_ANNOTATION)):
                    per_pod[name] = ("already bound with a different "
                                     "allocation")
                continue  # identical resend: no-op, claims stand
            if ann is None:
                ann = (pod.get("metadata") or {}).get("annotations") or {}
            chips, coord = _pod_claims(ann)
            reasons = []
            for prefix in sorted(chips):
                owner = self._chip_claims.get((node_name, prefix))
                if owner is not None and owner != name:
                    reasons.append(f"chip {prefix} on {node_name} "
                                   f"taken by {owner}")
                    continue
                rival = batch_chips.get((node_name, prefix))
                if rival is not None and rival != name:
                    reasons.append(f"chip {prefix} on {node_name} "
                                   f"claimed twice in batch (by {rival})")
                    continue
                batch_chips[(node_name, prefix)] = name
            if coord is not None:
                cnode, port, gang = coord
                entry = self._coord_claims.get((cnode, port))
                if entry is not None and entry[0] != gang:
                    reasons.append(f"coordinator port {port} on {cnode} "
                                   f"taken by gang {entry[0]}")
                else:
                    rival_gang = batch_coords.get((cnode, port))
                    if rival_gang is not None and rival_gang != gang:
                        reasons.append(f"coordinator port {port} on "
                                       f"{cnode} claimed twice in batch")
                    else:
                        batch_coords[(cnode, port)] = gang
            if reasons:
                per_pod[name] = "; ".join(reasons)
        return per_pod

    def create_pod(self, pod: dict) -> dict:
        with self._lock:
            name = pod["metadata"]["name"]
            if name in self._pods:
                raise Conflict(f"pod {name} exists")
            self._check_hard_quota_locked(pod)
            stored = copy.deepcopy(pod)
            stored.setdefault("spec", {})
            stored.setdefault("status", {"phase": "Pending"})
            self._pods[name] = stored
            self._index_pod_locked(stored)
            self._notify_locked("pod", "added", stored)
            out = copy.deepcopy(stored)
        # admission mints the pod's trace: the deterministic per-pod
        # trace id starts its timeline here, before any scheduler sees it
        obs.event("admitted", pod=name, proc=_OBS_PROC)
        return out

    def get_pod(self, name: str) -> dict:
        with self._lock:
            if name not in self._pods:
                raise NotFound(f"pod {name}")
            return copy.deepcopy(self._pods[name])

    def list_pods(self, node_name: str | None = None,
                  phase: str | None = None, bound: bool = False) -> list:
        """List pods, optionally narrowed by the secondary indexes:
        ``node_name`` (pods-by-node), ``phase`` (pods-by-phase), or
        ``bound=True`` (any pod with ``spec.nodeName`` set — the union of
        the node index). Each narrowed form copies only its slice, so the
        eviction / victim-scan / gang-lookup consumers stop paying
        O(all-pods) per call."""
        with self._lock:
            if node_name is not None:
                names = self._pods_by_node.get(node_name, ())
            elif bound:
                names = [n for bucket in self._pods_by_node.values()
                         for n in bucket]
            elif phase is not None:
                names = self._pods_by_phase.get(phase, ())
            else:
                names = self._pods
            pods = [self._pods[n] for n in sorted(names) if n in self._pods]
            if phase is not None:
                pods = [p for p in pods
                        if (p.get("status") or {}).get("phase") == phase]
            return [copy.deepcopy(p) for p in pods]

    def _allocation_guard_locked(self, name: str,
                                 new_ann: dict) -> str | None:
        # Always called with self._lock held. A BOUND pod's allocation
        # annotations (device allocation + gang process contract) are
        # immutable: they are the committed placement every scheduler
        # replica's accounting derives from, so rewriting them (a losing
        # replica's stale stamp) would silently swap the pod's chips
        # under the whole control plane. Same-value rewrites (lost-reply
        # resends) stay allowed; everything else on the pod too.
        pod = self._pods[name]
        if not (pod.get("spec") or {}).get("nodeName"):
            return None
        cur = (pod.get("metadata") or {}).get("annotations") or {}
        for key in (codec.POD_ANNOTATION_KEY, _GANG_PROCESS_ANNOTATION):
            if cur.get(key) != (new_ann or {}).get(key):
                return (f"pod {name} is bound; its allocation "
                        f"annotations are immutable")
        return None

    def update_pod_annotations(self, name: str, annotations: dict) -> dict:
        """Replace a pod's annotations, nothing else — the guarantee
        `UpdatePodMetadata` provides (`kubeinterface.go:175-193`). A
        bound pod's claim indexes follow its annotations (deindex old,
        index new) so the arbiter always sees committed state, and its
        ALLOCATION annotations are immutable (see
        `_allocation_guard_locked`)."""
        probe("apiserver.update_pod_annotations")
        with self._lock:
            if name not in self._pods:
                raise NotFound(f"pod {name}")
            reason = self._allocation_guard_locked(name, annotations)
            if reason:
                raise Conflict(reason, per_pod={name: reason})
            pod = self._pods[name]
            self._deindex_pod_locked(pod)
            meta = pod.setdefault("metadata", {})
            meta["annotations"] = copy.deepcopy(annotations)
            self._index_pod_locked(pod)
            self._notify_locked("pod", "modified", pod)
            return copy.deepcopy(pod)

    def update_pod_annotations_many(self, annotations: dict) -> None:
        """Batched `update_pod_annotations`: {pod name -> annotation dict}
        applied in one request / one lock acquisition, validated up front
        so a missing pod (NotFound) or an immutable-allocation violation
        (Conflict) fails the batch before anything is written — with
        per-pod detail, so the caller can drop exactly the bad pods and
        re-send the rest instead of abandoning the whole batch. This is
        the multi-key write the gang paths use so N members' stamps ride
        one transport round trip instead of N."""
        probe("apiserver.update_pod_annotations_many")
        with self._lock:
            missing = {name: "not found" for name in annotations
                       if name not in self._pods}
            if missing:
                raise NotFound(f"pods not found: {sorted(missing)}",
                               per_pod=missing)
            refused = {}
            for name, ann in annotations.items():
                reason = self._allocation_guard_locked(name, ann)
                if reason:
                    refused[name] = reason
            if refused:
                raise Conflict(
                    f"allocation annotations immutable for "
                    f"{sorted(refused)}", per_pod=refused)
            changed = []
            for name, ann in annotations.items():
                pod = self._pods[name]
                self._deindex_pod_locked(pod)
                meta = pod.setdefault("metadata", {})
                meta["annotations"] = copy.deepcopy(ann)
                self._index_pod_locked(pod)
                changed.append(pod)
            for pod in changed:
                self._notify_locked("pod", "modified", pod)

    def bind_pod(self, name: str, node_name: str) -> None:
        """The bind subresource: sets spec.nodeName exactly once. The
        conflict arbiter also refuses a bind whose annotation claims a
        chip another bound pod holds or a coordinator port promised to a
        different gang — re-applying the same bind for the same node
        stays a converging no-op. The decision is traced as an
        ``arbiter_commit`` span continuing the caller's bind span (wire
        header or in-process context)."""
        probe("apiserver.bind_pod")
        wall, t0 = obs.wall_now(), time.perf_counter()
        try:
            with self._lock:
                if name not in self._pods:
                    raise NotFound(f"pod {name}")
                pod = self._pods[name]
                bound = pod.get("spec", {}).get("nodeName")
                if bound and bound != node_name:
                    raise Conflict(f"pod {name} already bound to {bound}")
                if not bound:
                    conflicts = self._bind_conflicts_locked(
                        {name: node_name}, {})
                    if conflicts:
                        raise Conflict(f"pod {name}: {conflicts[name]}",
                                       per_pod=conflicts)
                self._deindex_pod_locked(pod)
                pod.setdefault("spec", {})["nodeName"] = node_name
                pod.setdefault("status", {})["phase"] = "Scheduled"
                self._index_pod_locked(pod)
                self._notify_locked("pod", "modified", pod)
        except Conflict as err:
            obs.record_span("arbiter_commit", wall,
                            time.perf_counter() - t0, pod=name,
                            proc=_OBS_PROC, outcome="conflict",
                            reason=str(err))
            raise
        except NotFound:
            obs.record_span("arbiter_commit", wall,
                            time.perf_counter() - t0, pod=name,
                            proc=_OBS_PROC, outcome="not_found")
            raise
        obs.record_span("arbiter_commit", wall, time.perf_counter() - t0,
                        pod=name, proc=_OBS_PROC, node=node_name,
                        outcome="committed")

    def bind_many(self, bindings: dict, annotations: dict) -> None:
        """Atomically annotate and bind a pod-set (gang commit): either
        every pod binds or none does. ``bindings``: pod name -> node
        name; ``annotations``: pod name -> annotation dict.

        This is the conflict-commit arbiter for N optimistic scheduler
        replicas over shared state (Omega-style): a bind that would
        re-bind a pod, oversubscribe a chip, or take another gang's
        coordinator port refuses the WHOLE batch — gangs stay
        all-or-nothing across competing replicas — and the Conflict /
        NotFound carries per-pod reasons so the losing replica's binder
        forgets + requeues exactly the refused pods, never retries them
        blind. Every pod's verdict is traced as an ``arbiter_commit``
        span continuing that pod's bind span (per-pod contexts carried
        by the batch header / in-process batch context)."""
        probe("apiserver.bind_many")
        wall, t0 = obs.wall_now(), time.perf_counter()
        try:
            with self._lock:
                missing = {name: "not found" for name in bindings
                           if name not in self._pods}
                if missing:
                    raise NotFound(f"pods not found: {sorted(missing)}",
                                   per_pod=missing)
                conflicts = self._bind_conflicts_locked(bindings, annotations)
                if conflicts:
                    first = next(iter(sorted(conflicts)))
                    raise Conflict(
                        f"bind refused for {len(conflicts)} pod(s), e.g. "
                        f"{first}: {conflicts[first]}", per_pod=conflicts)
                changed = []
                for name, node_name in bindings.items():
                    pod = self._pods[name]
                    self._deindex_pod_locked(pod)
                    meta = pod.setdefault("metadata", {})
                    if name in annotations:
                        meta["annotations"] = copy.deepcopy(annotations[name])
                    # a bindings-only entry (no annotations key) keeps the
                    # pod's existing annotations: a resend must never wipe a
                    # bound pod's allocation record and release its claims
                    pod.setdefault("spec", {})["nodeName"] = node_name
                    pod.setdefault("status", {})["phase"] = "Scheduled"
                    self._index_pod_locked(pod)
                    changed.append(pod)
                for pod in changed:
                    self._notify_locked("pod", "modified", pod)
        except (Conflict, NotFound) as err:
            dur = time.perf_counter() - t0
            outcome = "conflict" if isinstance(err, Conflict) \
                else "not_found"
            for name in sorted(bindings):
                # the WHOLE batch is refused (gang atomicity): innocents
                # record the batch-mate's reason so the timeline says why
                obs.record_span("arbiter_commit", wall, dur, pod=name,
                                proc=_OBS_PROC, outcome=outcome,
                                reason=err.per_pod.get(name)
                                or "batch refused")
            raise
        dur = time.perf_counter() - t0
        for name, node_name in bindings.items():
            obs.record_span("arbiter_commit", wall, dur, pod=name,
                            proc=_OBS_PROC, node=node_name,
                            outcome="committed")

    def delete_pod(self, name: str) -> None:
        probe("apiserver.delete_pod")
        with self._lock:
            pod = self._pods.pop(name, None)
            if pod is None:
                # raise like the HTTP transport's 404 and real Kubernetes
                # (see delete_node) — this is what keeps the lifecycle
                # controller's externally-deleted-pod guard alive
                raise NotFound(f"pod {name}")
            self._deindex_pod_locked(pod)
            self._notify_locked("pod", "deleted", pod)

    # ---- persistent volumes / claims ---------------------------------------
    # The volume-binding surface the scheduler consumes
    # (`volumebinder/volume_binder.go:1-74`,
    # `predicates.go:1443-1465`): PVCs reference storage demands, PVs
    # carry capacity + node affinity, and `bind_volume` commits a
    # claim<->volume pairing atomically (both objects flip to Bound).
    #
    # PVC: {"metadata": {"name"}, "spec": {"resources": {"requests":
    #   {"storage": "10Gi"}}, "storageClassName", "volumeName"?}}
    # PV:  {"metadata": {"name"}, "spec": {"capacity": {"storage": ...},
    #   "storageClassName", "nodeAffinity": {"required":
    #   {"nodeSelectorTerms": [...]}}, "claimRef"?}}

    def create_pvc(self, pvc: dict) -> dict:
        with self._lock:
            name = pvc["metadata"]["name"]
            if name in self._pvcs:
                raise Conflict(f"pvc {name} exists")
            stored = copy.deepcopy(pvc)
            stored.setdefault("status", {"phase": "Pending"})
            self._pvcs[name] = stored
            self._notify_locked("pvc", "added", stored)
            return copy.deepcopy(stored)

    def get_pvc(self, name: str) -> dict:
        with self._lock:
            if name not in self._pvcs:
                raise NotFound(f"pvc {name}")
            return copy.deepcopy(self._pvcs[name])

    def list_pvcs(self) -> list:
        with self._lock:
            return [copy.deepcopy(p) for _, p in sorted(self._pvcs.items())]

    def delete_pvc(self, name: str) -> None:
        with self._lock:
            pvc = self._pvcs.pop(name, None)
            if pvc is not None:
                self._notify_locked("pvc", "deleted", pvc)

    def create_pv(self, pv: dict) -> dict:
        with self._lock:
            name = pv["metadata"]["name"]
            if name in self._pvs:
                raise Conflict(f"pv {name} exists")
            stored = copy.deepcopy(pv)
            stored.setdefault("status", {"phase": "Available"})
            self._pvs[name] = stored
            self._notify_locked("pv", "added", stored)
            return copy.deepcopy(stored)

    def get_pv(self, name: str) -> dict:
        with self._lock:
            if name not in self._pvs:
                raise NotFound(f"pv {name}")
            return copy.deepcopy(self._pvs[name])

    def list_pvs(self) -> list:
        with self._lock:
            return [copy.deepcopy(p) for _, p in sorted(self._pvs.items())]

    def delete_pv(self, name: str) -> None:
        with self._lock:
            pv = self._pvs.pop(name, None)
            if pv is not None:
                self._notify_locked("pv", "deleted", pv)

    def patch_pv_spec(self, name: str, spec_patch: dict) -> dict:
        """Strategic-merge patch of a PV's spec — the real binder's first
        write (`kubeclient.bind_volume` PATCHes ``claimRef``). Conflicts
        if the patch re-claims a PV already claimed elsewhere."""
        with self._lock:
            if name not in self._pvs:
                raise NotFound(f"pv {name}")
            pv = self._pvs[name]
            ref = (spec_patch or {}).get("claimRef")
            cur = (pv.get("spec") or {}).get("claimRef")
            if ref and cur and cur.get("name") != ref.get("name"):
                raise Conflict(f"pv {name} already claimed by "
                               f"{cur.get('name')}")
            _merge(pv.setdefault("spec", {}), spec_patch or {})
            if pv["spec"].get("claimRef"):
                pv.setdefault("status", {})["phase"] = "Bound"
            self._notify_locked("pv", "modified", pv)
            return copy.deepcopy(pv)

    def patch_pvc_spec(self, name: str, spec_patch: dict) -> dict:
        """Strategic-merge patch of a PVC's spec (``volumeName`` — the
        binder's second write)."""
        with self._lock:
            if name not in self._pvcs:
                raise NotFound(f"pvc {name}")
            pvc = self._pvcs[name]
            vol = (spec_patch or {}).get("volumeName")
            cur = (pvc.get("spec") or {}).get("volumeName")
            if vol and cur and cur != vol:
                raise Conflict(f"pvc {name} already bound to {cur}")
            _merge(pvc.setdefault("spec", {}), spec_patch or {})
            if pvc["spec"].get("volumeName"):
                pvc.setdefault("status", {})["phase"] = "Bound"
            self._notify_locked("pvc", "modified", pvc)
            return copy.deepcopy(pvc)

    def bind_volume(self, pv_name: str, claim_name: str) -> None:
        """Atomically pair a PV with a PVC: PV gains ``claimRef`` and PVC
        gains ``volumeName``; both flip to Bound. Conflict if either side
        is already paired elsewhere. One copy of the conflict semantics:
        delegates to the two spec-patch methods (the RLock is reentrant),
        with the PVC side pre-checked so a conflicting claim cannot
        half-claim the PV."""
        with self._lock:
            if pv_name not in self._pvs:
                raise NotFound(f"pv {pv_name}")
            if claim_name not in self._pvcs:
                raise NotFound(f"pvc {claim_name}")
            bound = (self._pvcs[claim_name].get("spec") or {}) \
                .get("volumeName")
            if bound and bound != pv_name:
                raise Conflict(f"pvc {claim_name} already bound to {bound}")
            self.patch_pv_spec(pv_name, {"claimRef": {"name": claim_name}})
            self.patch_pvc_spec(claim_name, {"volumeName": pv_name})

    # ---- pod disruption budgets -------------------------------------------
    # Minimal PDB surface the preemption path consumes
    # (`generic_scheduler.go:254,674-699` reads PDBs to minimize violations):
    # {"metadata": {"name"}, "spec": {"selector": {"matchLabels": {...}},
    #  "minAvailable": N}}.

    def create_pdb(self, pdb: dict) -> dict:
        with self._lock:
            name = pdb["metadata"]["name"]
            if name in self._pdbs:
                raise Conflict(f"pdb {name} exists")
            self._pdbs[name] = copy.deepcopy(pdb)
            self._notify_locked("pdb", "added", self._pdbs[name])
            return copy.deepcopy(self._pdbs[name])

    def list_pdbs(self) -> list:
        with self._lock:
            return [copy.deepcopy(p) for _, p in sorted(self._pdbs.items())]

    def delete_pdb(self, name: str) -> None:
        with self._lock:
            pdb = self._pdbs.pop(name, None)
            if pdb is not None:
                self._notify_locked("pdb", "deleted", pdb)

    # ---- selector owners (Services / RCs / RSs / StatefulSets) -------------
    # The reference's SelectorSpreadPriority spreads by the label
    # selectors of the objects that OWN the pod (`selector_spreading.go`,
    # getSelectors) — these four kinds are its listers.

    def _create_owner(self, kind: str, obj: dict) -> dict:
        with self._lock:
            name = obj["metadata"]["name"]
            store = self._owners[kind]
            if name in store:
                raise Conflict(f"{kind} {name} exists")
            store[name] = copy.deepcopy(obj)
            self._notify_locked(kind, "added", store[name])
            return copy.deepcopy(store[name])

    def _list_owners(self, kind: str) -> list:
        with self._lock:
            return [copy.deepcopy(o)
                    for _, o in sorted(self._owners[kind].items())]

    def _delete_owner(self, kind: str, name: str) -> None:
        with self._lock:
            obj = self._owners[kind].pop(name, None)
            if obj is not None:
                self._notify_locked(kind, "deleted", obj)

    def create_service(self, svc: dict) -> dict:
        return self._create_owner("service", svc)

    def list_services(self) -> list:
        return self._list_owners("service")

    def delete_service(self, name: str) -> None:
        self._delete_owner("service", name)

    def create_rc(self, rc: dict) -> dict:
        return self._create_owner("rc", rc)

    def list_rcs(self) -> list:
        return self._list_owners("rc")

    def delete_rc(self, name: str) -> None:
        self._delete_owner("rc", name)

    def create_rs(self, rs: dict) -> dict:
        return self._create_owner("rs", rs)

    def list_rss(self) -> list:
        return self._list_owners("rs")

    def delete_rs(self, name: str) -> None:
        self._delete_owner("rs", name)

    def create_statefulset(self, ss: dict) -> dict:
        return self._create_owner("statefulset", ss)

    def list_statefulsets(self) -> list:
        return self._list_owners("statefulset")

    def delete_statefulset(self, name: str) -> None:
        self._delete_owner("statefulset", name)

    # ---- events ------------------------------------------------------------
    # The reference records k8s Events on scheduling outcomes
    # (`scheduler.go:198,242,272`): FailedScheduling / Preempted /
    # Scheduled, deduplicated by (involved, reason, message) with a count.

    def record_event(self, involved_kind: str, involved_name: str,
                     event_type: str, reason: str, message: str) -> dict:
        key = (involved_kind, involved_name, reason, message)
        with self._lock:
            ev = self._events.get(key)
            if ev is not None:
                ev["count"] += 1
                self._notify_locked("event", "modified", ev)
                return copy.deepcopy(ev)
            ev = {"involvedObject": {"kind": involved_kind,
                                     "name": involved_name},
                  "type": event_type, "reason": reason, "message": message,
                  "count": 1}
            self._events[key] = ev
            while len(self._events) > self.MAX_EVENTS:
                self._events.pop(next(iter(self._events)))
            self._notify_locked("event", "added", ev)
            return copy.deepcopy(ev)

    def record_events(self, events: list) -> None:
        """Batched ``record_event``: a list of ``{kind, name, type,
        reason, message}`` dicts recorded in one request / one lock pass
        (the RLock is reentrant) — the binder pool's per-batch Scheduled
        stamps ride one round trip instead of one per pod."""
        with self._lock:
            for e in events:
                self.record_event(e.get("kind", "Pod"), e["name"],
                                  e.get("type", "Normal"), e["reason"],
                                  e.get("message", ""))

    def list_events(self, involved_name: str | None = None) -> list:
        with self._lock:
            out = list(self._events.values())
            if involved_name is not None:
                out = [e for e in out
                       if e["involvedObject"]["name"] == involved_name]
            return [copy.deepcopy(e) for e in out]

    # ---- durability (cluster/wal.py) ---------------------------------------

    _STORES = ("nodes", "pods", "pdbs", "pvcs", "pvs", "quotas")

    def dump_state(self) -> dict:
        """JSON-serializable full object state for WAL snapshots.
        Reentrant under the server lock: the event log calls this from
        inside a watch notification (the mutator's RLock is held), which
        is exactly what makes the snapshot consistent with its sequence
        number."""
        with self._lock:
            out: dict = {store: copy.deepcopy(getattr(self, f"_{store}"))
                         for store in self._STORES}
            out["owners"] = copy.deepcopy(self._owners)
            out["events"] = [copy.deepcopy(ev)
                             for ev in self._events.values()]
            return out

    def snapshot_with(self, seq_fn):
        """``(dump_state(), seq_fn())`` atomically: under the mutation
        lock nothing can notify, so the event-log cursor ``seq_fn``
        reads cannot move between the two — the WAL snapshot's state and
        sequence number always agree."""
        with self._lock:
            return self.dump_state(), seq_fn()

    def restore_state(self, state: dict) -> None:
        """Load a snapshot (WAL recovery): replaces all object state and
        rebuilds every secondary index and claim table. Notifies nobody —
        watchers resume through the event log's sequence numbers, not a
        replayed storm of synthetic events."""
        with self._lock:
            for store in self._STORES:
                setattr(self, f"_{store}",
                        copy.deepcopy(state.get(store) or {}))
            owners = state.get("owners") or {}
            self._owners = {k: copy.deepcopy(owners.get(k) or {})
                            for k in ("service", "rc", "rs", "statefulset")}
            self._events = {}
            for ev in state.get("events") or []:
                inv = ev.get("involvedObject") or {}
                key = (inv.get("kind"), inv.get("name"),
                       ev.get("reason"), ev.get("message"))
                self._events[key] = copy.deepcopy(ev)
            self._rebuild_indexes_locked()

    def restore_object(self, kind: str, event: str, obj: dict) -> None:
        """Apply ONE replayed watch record to state, without notifying —
        the WAL recovery state machine. Watch events carry whole
        objects, so added/modified store and deleted removes."""
        with self._lock:
            if kind == "event":
                inv = obj.get("involvedObject") or {}
                key = (inv.get("kind"), inv.get("name"),
                       obj.get("reason"), obj.get("message"))
                if event == "deleted":
                    self._events.pop(key, None)
                else:
                    self._events[key] = copy.deepcopy(obj)
                return
            name = (obj.get("metadata") or {}).get("name")
            if not name:
                return
            if kind == "pod":
                existing = self._pods.get(name)
                if existing is not None:
                    self._deindex_pod_locked(existing)
                if event == "deleted":
                    self._pods.pop(name, None)
                else:
                    stored = copy.deepcopy(obj)
                    self._pods[name] = stored
                    self._index_pod_locked(stored)
                return
            if kind == "quota":
                if event == "deleted":
                    self._quotas.pop(name, None)
                else:
                    self._quotas[name] = copy.deepcopy(
                        obj.get("spec") or {})
                return
            store = {"node": self._nodes, "pdb": self._pdbs,
                     "pvc": self._pvcs, "pv": self._pvs}.get(kind)
            if store is None:
                store = self._owners.get(kind)
            if store is None:
                return  # unknown kind in the log: skip, never fatal
            if event == "deleted":
                store.pop(name, None)
            else:
                store[name] = copy.deepcopy(obj)

    def _rebuild_indexes_locked(self) -> None:
        # Always called with self._lock held, after a wholesale state
        # replacement: the secondary indexes and claim tables are pure
        # derivations of the pod store.
        self._pods_by_node = {}
        self._pods_by_phase = {}
        self._chip_claims = {}
        self._coord_claims = {}
        self._tenant_chips = {}
        self._pod_tenant_chips = {}
        for pod in self._pods.values():
            self._index_pod_locked(pod)

    # ---- watch -------------------------------------------------------------

    def add_watcher(self, fn) -> None:
        """fn(kind, event, obj) called under no lock guarantee ordering by
        arrival; used by the scheduler's informer loop."""
        with self._lock:
            self._watchers.append(fn)

    def _notify_locked(self, kind: str, event: str, obj: dict) -> None:
        # Always called with self._lock held: the lock is what gives every
        # watcher the same total event order the watch protocol promises.
        obj_copy = copy.deepcopy(obj)
        for fn in list(self._watchers):
            fn(kind, event, obj_copy)

"""A thread-safe in-memory stand-in for the Kubernetes API server surface
this framework uses: node/pod objects (plain JSON-shaped dicts), metadata
patching, binding, and change notification.

Only the operations the reference performs are modeled
(`kubeinterface.go:145-193`, scheduler bind at `scheduler.go:405-417`):
get/patch node metadata, get/update pod annotations, bind.
"""

from __future__ import annotations

import copy
import threading


class NotFound(KeyError):
    pass


class Conflict(RuntimeError):
    pass


def _merge(dst: dict, patch: dict) -> None:
    """Strategic-merge-patch for the metadata shapes we carry: dicts merge
    recursively, everything else replaces."""
    for key, val in patch.items():
        if isinstance(val, dict) and isinstance(dst.get(key), dict):
            _merge(dst[key], val)
        else:
            dst[key] = copy.deepcopy(val)


class InMemoryAPIServer:
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: dict = {}
        self._pods: dict = {}
        self._pdbs: dict = {}
        self._pvcs: dict = {}
        self._pvs: dict = {}
        # selector owners for SelectorSpreadPriority
        # (`selector_spreading.go`: services, RCs, RSs, StatefulSets)
        self._owners: dict = {k: {} for k in
                              ("service", "rc", "rs", "statefulset")}
        # insertion-ordered (kind, name, reason, message) -> event; the
        # key IS the dedup identity, so record_event is O(1) not a scan
        self._events: dict = {}
        self._watchers: list = []
        # Secondary pod indexes, maintained under self._lock by every pod
        # mutator (the same discipline as _notify_locked): lifecycle
        # eviction, gang lookup, and preemption's victim scan read
        # pods-by-node / bound / by-phase slices instead of sweeping
        # every pod in the cluster.
        self._pods_by_node: dict = {}   # node name -> {pod names}
        self._pods_by_phase: dict = {}  # status.phase -> {pod names}

    MAX_EVENTS = 5000

    # ---- nodes -------------------------------------------------------------

    def create_node(self, node: dict) -> dict:
        with self._lock:
            name = node["metadata"]["name"]
            self._nodes[name] = copy.deepcopy(node)
            self._notify_locked("node", "added", self._nodes[name])
            return copy.deepcopy(self._nodes[name])

    def get_node(self, name: str) -> dict:
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name}")
            return copy.deepcopy(self._nodes[name])

    def list_nodes(self) -> list:
        with self._lock:
            return [copy.deepcopy(n) for _, n in sorted(self._nodes.items())]

    def patch_node_metadata(self, name: str, metadata_patch: dict) -> dict:
        """Strategic-merge-patch of node metadata
        (`kubeinterface.go:145-158`). A patch that changes nothing
        delivers NO watch event: every node event is an invalidation
        source for the scheduler's fit memo (and requeues unschedulable
        pods), so an idempotent re-advertise must not masquerade as a
        node change."""
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name}")
            meta = self._nodes[name].setdefault("metadata", {})
            before = copy.deepcopy(meta)
            _merge(meta, metadata_patch)
            if meta != before:
                self._notify_locked("node", "modified", self._nodes[name])
            return copy.deepcopy(self._nodes[name])

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is None:
                # raise like the HTTP transport's 404 and real Kubernetes:
                # a caller distinguishing "I deleted it" from "it was
                # already gone" (eviction, preemption) needs the signal
                raise NotFound(f"node {name}")
            self._notify_locked("node", "deleted", node)

    # ---- pods --------------------------------------------------------------

    def _index_pod_locked(self, pod: dict) -> None:
        # Always called with self._lock held, right after a pod mutation:
        # the index entry must be atomic with the object state it mirrors.
        name = pod["metadata"]["name"]
        node = (pod.get("spec") or {}).get("nodeName")
        phase = (pod.get("status") or {}).get("phase")
        if node:
            self._pods_by_node.setdefault(node, set()).add(name)
        if phase:
            self._pods_by_phase.setdefault(phase, set()).add(name)

    def _deindex_pod_locked(self, pod: dict) -> None:
        # Always called with self._lock held, BEFORE a mutation that may
        # move the pod between index buckets (bind, delete).
        name = pod["metadata"]["name"]
        node = (pod.get("spec") or {}).get("nodeName")
        phase = (pod.get("status") or {}).get("phase")
        if node:
            bucket = self._pods_by_node.get(node)
            if bucket is not None:
                bucket.discard(name)
                if not bucket:
                    del self._pods_by_node[node]
        if phase:
            bucket = self._pods_by_phase.get(phase)
            if bucket is not None:
                bucket.discard(name)
                if not bucket:
                    del self._pods_by_phase[phase]

    def create_pod(self, pod: dict) -> dict:
        with self._lock:
            name = pod["metadata"]["name"]
            if name in self._pods:
                raise Conflict(f"pod {name} exists")
            stored = copy.deepcopy(pod)
            stored.setdefault("spec", {})
            stored.setdefault("status", {"phase": "Pending"})
            self._pods[name] = stored
            self._index_pod_locked(stored)
            self._notify_locked("pod", "added", stored)
            return copy.deepcopy(stored)

    def get_pod(self, name: str) -> dict:
        with self._lock:
            if name not in self._pods:
                raise NotFound(f"pod {name}")
            return copy.deepcopy(self._pods[name])

    def list_pods(self, node_name: str | None = None,
                  phase: str | None = None, bound: bool = False) -> list:
        """List pods, optionally narrowed by the secondary indexes:
        ``node_name`` (pods-by-node), ``phase`` (pods-by-phase), or
        ``bound=True`` (any pod with ``spec.nodeName`` set — the union of
        the node index). Each narrowed form copies only its slice, so the
        eviction / victim-scan / gang-lookup consumers stop paying
        O(all-pods) per call."""
        with self._lock:
            if node_name is not None:
                names = self._pods_by_node.get(node_name, ())
            elif bound:
                names = [n for bucket in self._pods_by_node.values()
                         for n in bucket]
            elif phase is not None:
                names = self._pods_by_phase.get(phase, ())
            else:
                names = self._pods
            pods = [self._pods[n] for n in sorted(names) if n in self._pods]
            if phase is not None:
                pods = [p for p in pods
                        if (p.get("status") or {}).get("phase") == phase]
            return [copy.deepcopy(p) for p in pods]

    def update_pod_annotations(self, name: str, annotations: dict) -> dict:
        """Replace a pod's annotations, nothing else — the guarantee
        `UpdatePodMetadata` provides (`kubeinterface.go:175-193`)."""
        with self._lock:
            if name not in self._pods:
                raise NotFound(f"pod {name}")
            meta = self._pods[name].setdefault("metadata", {})
            meta["annotations"] = copy.deepcopy(annotations)
            self._notify_locked("pod", "modified", self._pods[name])
            return copy.deepcopy(self._pods[name])

    def update_pod_annotations_many(self, annotations: dict) -> None:
        """Batched `update_pod_annotations`: {pod name -> annotation dict}
        applied in one request / one lock acquisition, validated up front
        so a missing pod fails the batch before anything is written. This
        is the multi-key write the gang paths use so N members' stamps
        ride one transport round trip instead of N."""
        with self._lock:
            for name in annotations:
                if name not in self._pods:
                    raise NotFound(f"pod {name}")
            changed = []
            for name, ann in annotations.items():
                meta = self._pods[name].setdefault("metadata", {})
                meta["annotations"] = copy.deepcopy(ann)
                changed.append(self._pods[name])
            for pod in changed:
                self._notify_locked("pod", "modified", pod)

    def bind_pod(self, name: str, node_name: str) -> None:
        """The bind subresource: sets spec.nodeName exactly once."""
        with self._lock:
            if name not in self._pods:
                raise NotFound(f"pod {name}")
            pod = self._pods[name]
            bound = pod.get("spec", {}).get("nodeName")
            if bound and bound != node_name:
                raise Conflict(f"pod {name} already bound to {bound}")
            self._deindex_pod_locked(pod)
            pod.setdefault("spec", {})["nodeName"] = node_name
            pod.setdefault("status", {})["phase"] = "Scheduled"
            self._index_pod_locked(pod)
            self._notify_locked("pod", "modified", pod)

    def bind_many(self, bindings: dict, annotations: dict) -> None:
        """Atomically annotate and bind a pod-set (gang commit): either every
        pod binds or none does. ``bindings``: pod name -> node name;
        ``annotations``: pod name -> annotation dict."""
        with self._lock:
            for name, node_name in bindings.items():
                if name not in self._pods:
                    raise NotFound(f"pod {name}")
                bound = self._pods[name].get("spec", {}).get("nodeName")
                if bound and bound != node_name:
                    raise Conflict(f"pod {name} already bound to {bound}")
            changed = []
            for name, node_name in bindings.items():
                pod = self._pods[name]
                meta = pod.setdefault("metadata", {})
                meta["annotations"] = copy.deepcopy(annotations.get(name, {}))
                self._deindex_pod_locked(pod)
                pod.setdefault("spec", {})["nodeName"] = node_name
                pod.setdefault("status", {})["phase"] = "Scheduled"
                self._index_pod_locked(pod)
                changed.append(pod)
            for pod in changed:
                self._notify_locked("pod", "modified", pod)

    def delete_pod(self, name: str) -> None:
        with self._lock:
            pod = self._pods.pop(name, None)
            if pod is None:
                # raise like the HTTP transport's 404 and real Kubernetes
                # (see delete_node) — this is what keeps the lifecycle
                # controller's externally-deleted-pod guard alive
                raise NotFound(f"pod {name}")
            self._deindex_pod_locked(pod)
            self._notify_locked("pod", "deleted", pod)

    # ---- persistent volumes / claims ---------------------------------------
    # The volume-binding surface the scheduler consumes
    # (`volumebinder/volume_binder.go:1-74`,
    # `predicates.go:1443-1465`): PVCs reference storage demands, PVs
    # carry capacity + node affinity, and `bind_volume` commits a
    # claim<->volume pairing atomically (both objects flip to Bound).
    #
    # PVC: {"metadata": {"name"}, "spec": {"resources": {"requests":
    #   {"storage": "10Gi"}}, "storageClassName", "volumeName"?}}
    # PV:  {"metadata": {"name"}, "spec": {"capacity": {"storage": ...},
    #   "storageClassName", "nodeAffinity": {"required":
    #   {"nodeSelectorTerms": [...]}}, "claimRef"?}}

    def create_pvc(self, pvc: dict) -> dict:
        with self._lock:
            name = pvc["metadata"]["name"]
            if name in self._pvcs:
                raise Conflict(f"pvc {name} exists")
            stored = copy.deepcopy(pvc)
            stored.setdefault("status", {"phase": "Pending"})
            self._pvcs[name] = stored
            self._notify_locked("pvc", "added", stored)
            return copy.deepcopy(stored)

    def get_pvc(self, name: str) -> dict:
        with self._lock:
            if name not in self._pvcs:
                raise NotFound(f"pvc {name}")
            return copy.deepcopy(self._pvcs[name])

    def list_pvcs(self) -> list:
        with self._lock:
            return [copy.deepcopy(p) for _, p in sorted(self._pvcs.items())]

    def delete_pvc(self, name: str) -> None:
        with self._lock:
            pvc = self._pvcs.pop(name, None)
            if pvc is not None:
                self._notify_locked("pvc", "deleted", pvc)

    def create_pv(self, pv: dict) -> dict:
        with self._lock:
            name = pv["metadata"]["name"]
            if name in self._pvs:
                raise Conflict(f"pv {name} exists")
            stored = copy.deepcopy(pv)
            stored.setdefault("status", {"phase": "Available"})
            self._pvs[name] = stored
            self._notify_locked("pv", "added", stored)
            return copy.deepcopy(stored)

    def get_pv(self, name: str) -> dict:
        with self._lock:
            if name not in self._pvs:
                raise NotFound(f"pv {name}")
            return copy.deepcopy(self._pvs[name])

    def list_pvs(self) -> list:
        with self._lock:
            return [copy.deepcopy(p) for _, p in sorted(self._pvs.items())]

    def delete_pv(self, name: str) -> None:
        with self._lock:
            pv = self._pvs.pop(name, None)
            if pv is not None:
                self._notify_locked("pv", "deleted", pv)

    def patch_pv_spec(self, name: str, spec_patch: dict) -> dict:
        """Strategic-merge patch of a PV's spec — the real binder's first
        write (`kubeclient.bind_volume` PATCHes ``claimRef``). Conflicts
        if the patch re-claims a PV already claimed elsewhere."""
        with self._lock:
            if name not in self._pvs:
                raise NotFound(f"pv {name}")
            pv = self._pvs[name]
            ref = (spec_patch or {}).get("claimRef")
            cur = (pv.get("spec") or {}).get("claimRef")
            if ref and cur and cur.get("name") != ref.get("name"):
                raise Conflict(f"pv {name} already claimed by "
                               f"{cur.get('name')}")
            _merge(pv.setdefault("spec", {}), spec_patch or {})
            if pv["spec"].get("claimRef"):
                pv.setdefault("status", {})["phase"] = "Bound"
            self._notify_locked("pv", "modified", pv)
            return copy.deepcopy(pv)

    def patch_pvc_spec(self, name: str, spec_patch: dict) -> dict:
        """Strategic-merge patch of a PVC's spec (``volumeName`` — the
        binder's second write)."""
        with self._lock:
            if name not in self._pvcs:
                raise NotFound(f"pvc {name}")
            pvc = self._pvcs[name]
            vol = (spec_patch or {}).get("volumeName")
            cur = (pvc.get("spec") or {}).get("volumeName")
            if vol and cur and cur != vol:
                raise Conflict(f"pvc {name} already bound to {cur}")
            _merge(pvc.setdefault("spec", {}), spec_patch or {})
            if pvc["spec"].get("volumeName"):
                pvc.setdefault("status", {})["phase"] = "Bound"
            self._notify_locked("pvc", "modified", pvc)
            return copy.deepcopy(pvc)

    def bind_volume(self, pv_name: str, claim_name: str) -> None:
        """Atomically pair a PV with a PVC: PV gains ``claimRef`` and PVC
        gains ``volumeName``; both flip to Bound. Conflict if either side
        is already paired elsewhere. One copy of the conflict semantics:
        delegates to the two spec-patch methods (the RLock is reentrant),
        with the PVC side pre-checked so a conflicting claim cannot
        half-claim the PV."""
        with self._lock:
            if pv_name not in self._pvs:
                raise NotFound(f"pv {pv_name}")
            if claim_name not in self._pvcs:
                raise NotFound(f"pvc {claim_name}")
            bound = (self._pvcs[claim_name].get("spec") or {}) \
                .get("volumeName")
            if bound and bound != pv_name:
                raise Conflict(f"pvc {claim_name} already bound to {bound}")
            self.patch_pv_spec(pv_name, {"claimRef": {"name": claim_name}})
            self.patch_pvc_spec(claim_name, {"volumeName": pv_name})

    # ---- pod disruption budgets -------------------------------------------
    # Minimal PDB surface the preemption path consumes
    # (`generic_scheduler.go:254,674-699` reads PDBs to minimize violations):
    # {"metadata": {"name"}, "spec": {"selector": {"matchLabels": {...}},
    #  "minAvailable": N}}.

    def create_pdb(self, pdb: dict) -> dict:
        with self._lock:
            name = pdb["metadata"]["name"]
            if name in self._pdbs:
                raise Conflict(f"pdb {name} exists")
            self._pdbs[name] = copy.deepcopy(pdb)
            self._notify_locked("pdb", "added", self._pdbs[name])
            return copy.deepcopy(self._pdbs[name])

    def list_pdbs(self) -> list:
        with self._lock:
            return [copy.deepcopy(p) for _, p in sorted(self._pdbs.items())]

    def delete_pdb(self, name: str) -> None:
        with self._lock:
            pdb = self._pdbs.pop(name, None)
            if pdb is not None:
                self._notify_locked("pdb", "deleted", pdb)

    # ---- selector owners (Services / RCs / RSs / StatefulSets) -------------
    # The reference's SelectorSpreadPriority spreads by the label
    # selectors of the objects that OWN the pod (`selector_spreading.go`,
    # getSelectors) — these four kinds are its listers.

    def _create_owner(self, kind: str, obj: dict) -> dict:
        with self._lock:
            name = obj["metadata"]["name"]
            store = self._owners[kind]
            if name in store:
                raise Conflict(f"{kind} {name} exists")
            store[name] = copy.deepcopy(obj)
            self._notify_locked(kind, "added", store[name])
            return copy.deepcopy(store[name])

    def _list_owners(self, kind: str) -> list:
        with self._lock:
            return [copy.deepcopy(o)
                    for _, o in sorted(self._owners[kind].items())]

    def _delete_owner(self, kind: str, name: str) -> None:
        with self._lock:
            obj = self._owners[kind].pop(name, None)
            if obj is not None:
                self._notify_locked(kind, "deleted", obj)

    def create_service(self, svc: dict) -> dict:
        return self._create_owner("service", svc)

    def list_services(self) -> list:
        return self._list_owners("service")

    def delete_service(self, name: str) -> None:
        self._delete_owner("service", name)

    def create_rc(self, rc: dict) -> dict:
        return self._create_owner("rc", rc)

    def list_rcs(self) -> list:
        return self._list_owners("rc")

    def delete_rc(self, name: str) -> None:
        self._delete_owner("rc", name)

    def create_rs(self, rs: dict) -> dict:
        return self._create_owner("rs", rs)

    def list_rss(self) -> list:
        return self._list_owners("rs")

    def delete_rs(self, name: str) -> None:
        self._delete_owner("rs", name)

    def create_statefulset(self, ss: dict) -> dict:
        return self._create_owner("statefulset", ss)

    def list_statefulsets(self) -> list:
        return self._list_owners("statefulset")

    def delete_statefulset(self, name: str) -> None:
        self._delete_owner("statefulset", name)

    # ---- events ------------------------------------------------------------
    # The reference records k8s Events on scheduling outcomes
    # (`scheduler.go:198,242,272`): FailedScheduling / Preempted /
    # Scheduled, deduplicated by (involved, reason, message) with a count.

    def record_event(self, involved_kind: str, involved_name: str,
                     event_type: str, reason: str, message: str) -> dict:
        key = (involved_kind, involved_name, reason, message)
        with self._lock:
            ev = self._events.get(key)
            if ev is not None:
                ev["count"] += 1
                self._notify_locked("event", "modified", ev)
                return copy.deepcopy(ev)
            ev = {"involvedObject": {"kind": involved_kind,
                                     "name": involved_name},
                  "type": event_type, "reason": reason, "message": message,
                  "count": 1}
            self._events[key] = ev
            while len(self._events) > self.MAX_EVENTS:
                self._events.pop(next(iter(self._events)))
            self._notify_locked("event", "added", ev)
            return copy.deepcopy(ev)

    def record_events(self, events: list) -> None:
        """Batched ``record_event``: a list of ``{kind, name, type,
        reason, message}`` dicts recorded in one request / one lock pass
        (the RLock is reentrant) — the binder pool's per-batch Scheduled
        stamps ride one round trip instead of one per pod."""
        with self._lock:
            for e in events:
                self.record_event(e.get("kind", "Pod"), e["name"],
                                  e.get("type", "Normal"), e["reason"],
                                  e.get("message", ""))

    def list_events(self, involved_name: str | None = None) -> list:
        with self._lock:
            out = list(self._events.values())
            if involved_name is not None:
                out = [e for e in out
                       if e["involvedObject"]["name"] == involved_name]
            return [copy.deepcopy(e) for e in out]

    # ---- watch -------------------------------------------------------------

    def add_watcher(self, fn) -> None:
        """fn(kind, event, obj) called under no lock guarantee ordering by
        arrival; used by the scheduler's informer loop."""
        with self._lock:
            self._watchers.append(fn)

    def _notify_locked(self, kind: str, event: str, obj: dict) -> None:
        # Always called with self._lock held: the lock is what gives every
        # watcher the same total event order the watch protocol promises.
        obj_copy = copy.deepcopy(obj)
        for fn in list(self._watchers):
            fn(kind, event, obj_copy)

"""Watch-cache proxy: the horizontally-scalable control-plane fan-out tier.

One apiserver process cannot push watch deltas to 100k clients — the
encode is already shared (the event-log pump encodes each window once),
but the sends, the sockets, and the per-subscriber bookkeeping all live
on one box. Upstream kube-apiserver answers this with the watch cache:
a tier that holds ONE subscription against the source of truth and
re-serves thousands of watchers from a local event window. This module
is that tier for this control plane:

* **One upstream subscription.** The proxy dials the apiserver once
  (stream SUB via cluster/stream.py, negotiated down to JSON long-poll
  against an upgrade-less server) and feeds every pushed batch into its
  own ``_EventLog`` in ``attach=False`` mode — the log records nothing
  itself; it carries the UPSTREAM sequence numbers. Because the seq
  space is global (WAL-continued across apiserver restarts), resume is
  seq-exact THROUGH the proxy: a client can migrate between a proxy
  replica and the apiserver, in either direction, without a relist.
* **Downstream fan-out reuses the pump.** The proxy serves the
  identical dual-wire surface through ``_serve_transport`` — same
  framing, same typed-error mapping, same encode-once pump — so N
  downstream watchers cost the apiserver exactly one subscription's
  worth of load no matter what N is.
* **Reads from the mirror, writes forwarded.** GETs are served from a
  mirrored ``InMemoryAPIServer`` maintained by ``restore_object``
  replay (the WAL recovery primitive — watch events carry whole
  objects, so replay is idempotent upsert). Everything else is
  forwarded upstream through :meth:`HTTPAPIClient.forward`, a
  hop-transparent round trip: typed errors (429/403/404/409) are
  re-raised here so the proxy's OWN transport re-maps them to the
  identical status + error body the apiserver would have sent.
  Leases are deliberately NOT served locally — a lease answer must be
  fresh and atomic, and the mirror is neither.
* **Shared-nothing replicas behind APF.** Each proxy carries its own
  front door (``apf=``): an abusive tenant saturates only the replica
  its flows hash to, and the system band (leases, watch, health) stays
  exempt at every hop.

A cursor below the proxy's own floor is not necessarily a gap: the
upstream window is deeper (WAL-backed). The SUB path's
``on_subscribe`` hook and the long-poll watch route both call
:meth:`WatchCacheProxy._ensure_window` first, which replays the missing
prefix from upstream (``_EventLog.backfill``) so the subscriber resumes
seq-exact instead of relisting — this is what makes
direct-apiserver -> proxy migration lossless.
"""

from __future__ import annotations

import logging
import threading
import time

from kubegpu_tpu import metrics, obs
from kubegpu_tpu.cluster import stream
from kubegpu_tpu.cluster.apf import TooManyRequests
from kubegpu_tpu.cluster.apiserver import (Conflict, InMemoryAPIServer,
                                           NotFound, QuotaExceeded)
from kubegpu_tpu.cluster.httpapi import (HTTPAPIClient, _EventLog,
                                         _route_request, _serve_transport)

logger = logging.getLogger(__name__)

# Route tables the wire-contract analyzer checks (analysis/rules/wire.py,
# forward-table check): every first path segment a package client can
# reach must appear in one of these — LOCAL_ROUTES are GETs answered
# from the mirror + this process's own observability surface,
# FORWARDED_ROUTES go upstream through `forward`. A segment in neither
# is a request the proxy would 404 that the apiserver would serve: a
# hole in the hop.
LOCAL_ROUTES = frozenset({
    "healthz", "metrics", "debug", "watch", "nodes", "pods", "pvcs",
    "pvs", "pdbs", "quotas", "services", "rcs", "rss", "statefulsets",
    "events",
})
FORWARDED_ROUTES = frozenset({
    "nodes", "pods", "podannotations", "bindmany", "pvcs", "pvs",
    "bindvolume", "quotas", "pdbs", "services", "rcs", "rss",
    "statefulsets", "events", "leases",
})

# Mirror bootstrap: every listable kind, with the list route that
# carries it. Ordered like the apiserver's own stores; quota lists as
# {tenant: spec} rather than objects, converted below.
_MIRROR_LISTS = (
    ("node", "/nodes"),
    ("pod", "/pods"),
    ("pvc", "/pvcs"),
    ("pv", "/pvs"),
    ("pdb", "/pdbs"),
    ("service", "/services"),
    ("rc", "/rcs"),
    ("rs", "/rss"),
    ("statefulset", "/statefulsets"),
    ("quota", "/quotas"),
    ("event", "/events"),
)

# A since-cursor far beyond any real head: the watch route answers it
# with an empty relist carrying the current head seq + epoch — the
# cheapest "where are you" probe the wire offers.
_HEAD_PROBE = 1 << 62


class WatchCacheProxy:
    """One proxy replica: sync, subscribe upstream, serve downstream.

    Construction blocks until the first mirror sync succeeds (a proxy
    that cannot reach its upstream has nothing to serve), then starts
    the upstream consumer thread and the downstream dual-wire server.
    ``proxy.url`` is the address clients point at; :meth:`stop` tears
    the whole replica down.
    """

    def __init__(self, upstream_url: str, name: str = "proxy",
                 host: str = "127.0.0.1", port: int = 0,
                 wire: str = stream.WIRE_STREAM, apf=None,
                 limit: int = 10000, stream_wire: bool = True,
                 upstream_batch_s: float = 0.0):
        self.upstream_url = upstream_url
        self.name = name
        self._apf = apf
        self._upstream_batch_s = upstream_batch_s
        self._stop = threading.Event()
        self._conn_lock = threading.Lock()
        self._sub_conn = None  # live upstream SUB connection, for stop()
        # the upstream leg reports its bytes as wire="proxy" so a
        # fronted deployment's apiserver-side load is measurable apart
        # from the client legs
        self._upstream = HTTPAPIClient(upstream_url, wire=wire,
                                       transport_label=stream.WIRE_PROXY)
        self._mirror = InMemoryAPIServer()
        self._log = _EventLog(self._mirror, limit=limit, attach=False)
        # racer: single-writer -- cursor/epoch are written by __init__
        # (before the consumer thread exists) and then only by the
        # consumer thread; downstream handlers never read them
        self._cursor = 0
        self._epoch = None
        self._sync()
        self._thread = threading.Thread(target=self._upstream_loop,
                                        daemon=True,
                                        name=f"{name}-upstream")
        self._thread.start()
        self._server, self.url = _serve_transport(
            self._dispatch, self._log, host=host, port=port,
            stream_wire=stream_wire, on_subscribe=self._ensure_window,
            role="proxy")

    # ---- downstream: dispatch ---------------------------------------------

    def _dispatch(self, method: str, parts: list, query: dict, body,
                  peer: str):
        """The proxy's admission + routing path, shaped exactly like
        serve_api's: the replica's own APF front door first (so a
        flooding tenant is shed HERE, its shard, not upstream), then
        the local-or-forwarded route split."""
        if self._apf is not None:
            with self._apf.admit(method, parts, query, body, peer=peer):
                return self._route(method, parts, query, body)
        return self._route(method, parts, query, body)

    def _route(self, method: str, parts: list, query: dict, body):
        head = parts[0] if parts else ""
        if parts == ["watch"]:
            # long-poll resume may predate our window; the upstream's
            # is deeper — backfill before the relist check can fire
            self._ensure_window(int(query.get("since", 0)))
            return _route_request(self._mirror, self._log, method,
                                  parts, query, body)
        if method == "GET" and head in LOCAL_ROUTES:
            try:
                return _route_request(self._mirror, self._log, method,
                                      parts, query, body)
            except NotFound:
                if len(parts) >= 2:
                    # a point-GET can race the mirror's replication
                    # lag (object created upstream, event not yet
                    # applied here): the source of truth gets the
                    # final word before a client sees a false 404
                    return self._forward(method, parts, query, body)
                raise
        if head in FORWARDED_ROUTES:
            return self._forward(method, parts, query, body)
        return 404, {"error": f"no route {method} /{'/'.join(parts)}"}

    def _forward(self, method: str, parts: list, query: dict, body):
        """One upstream round trip, hop-transparent: raw status in,
        typed error re-raised out — the proxy's own transport then maps
        it back to the identical status + error body (retry_after_s and
        per_pod detail included), so a client cannot tell from an error
        whether a hop was in the path."""
        path = "/" + "/".join(parts)
        if query:
            path += "?" + "&".join(f"{k}={v}" for k, v in query.items())
        out = self._upstream.forward(method, path, body)
        status, doc = out
        if status == 429:
            raise HTTPAPIClient._server_error(TooManyRequests, doc)
        if status == 403:
            raise HTTPAPIClient._server_error(QuotaExceeded, doc)
        if status == 404:
            raise HTTPAPIClient._server_error(NotFound, doc)
        if status == 409:
            raise HTTPAPIClient._server_error(Conflict, doc)
        return status, doc

    def _ensure_window(self, since: int) -> None:
        """Deepen the local window to cover ``since`` when the upstream
        can replay it: a client migrating from the apiserver (or an
        older proxy life) presents a cursor below our floor that is NOT
        a real gap. On any upstream refusal — relist, epoch mismatch,
        non-200 — do nothing: the pump then sends the same honest
        relist the upstream gave us."""
        if since <= 0 or since >= self._log.floor():
            return
        status, doc = self._upstream.forward(
            "GET", f"/watch?since={since}&timeout=0")
        if status != 200 or not isinstance(doc, dict) \
                or doc.get("relist") or doc.get("epoch") != self._log.epoch:
            return
        self._log.backfill([tuple(ev) for ev in doc.get("events") or []],
                           since)

    # ---- upstream: the one subscription -----------------------------------

    def _sync(self) -> None:
        """Full resync: probe the upstream head, list every kind into a
        fresh mirror, adopt the head seq + epoch. Lists happen AFTER
        the head probe, so they may already include later writes —
        replaying the stream from the probed head over them converges
        (restore_object is an idempotent whole-object upsert, and a
        delete of an absent object is tolerated)."""
        status, doc = self._upstream.forward(
            "GET", f"/watch?since={_HEAD_PROBE}&timeout=0")
        if status != 200 or not isinstance(doc, dict):
            raise ConnectionError(
                f"upstream head probe answered HTTP {status}")
        head, epoch = int(doc["seq"]), doc.get("epoch")
        mirror = InMemoryAPIServer()
        for kind, path in _MIRROR_LISTS:
            status, listed = self._upstream.forward("GET", path)
            if status != 200 or not isinstance(listed, dict):
                raise ConnectionError(
                    f"upstream list {path} answered HTTP {status}")
            items = listed.get("items")
            if kind == "quota":
                for tenant, spec in (items or {}).items():
                    mirror.restore_object(
                        "quota", "added",
                        {"metadata": {"name": tenant}, "spec": spec})
            else:
                for obj in items or []:
                    mirror.restore_object(kind, "added", obj)
        self._mirror = mirror
        self._cursor = head
        self._epoch = epoch
        self._log.reset(head, epoch)
        logger.info("proxy %s synced at upstream seq %d (epoch %s)",
                    self.name, head, epoch)

    def _apply(self, out: dict) -> bool:
        """Apply one upstream watch batch: mirror first (a downstream
        GET must never see an object the event log already announced),
        then the local window, then the cursor. Returns False when the
        upstream declared our cursor unreplayable (relist) or changed
        identity (epoch) — the caller resyncs and resubscribes, and
        every downstream watcher inherits the honest relist through
        ``_EventLog.reset``."""
        if out.get("relist") or out.get("epoch") != self._epoch \
                or out["seq"] < self._cursor:
            self._sync()
            return False
        ts = out.get("ts") or 0.0
        if ts:
            now = time.time()  # analysis: disable=monotonic-time -- cross-process push-lag stamp, like the pump's
            metrics.PROXY_UPSTREAM_LAG_MS.observe(
                max(0.0, (now - ts) * 1e3))
        events = out.get("events") or []
        for ev in events:
            _seq, kind, event, obj = ev
            self._mirror.restore_object(kind, event, obj)
        self._log.ingest(events, out["seq"])
        self._cursor = out["seq"]
        metrics.PROXY_DOWNSTREAM_WATCHERS.labels(self.name).set(
            self._log.stream_subscriber_count())
        return True

    def _upstream_loop(self) -> None:
        obs.register_thread(f"{self.name}-upstream")
        warned = False
        while not self._stop.is_set():
            conn = None
            try:
                try:
                    conn = stream.StreamConn.connect(
                        self.upstream_url, 10.0,
                        label=stream.WIRE_PROXY)
                except stream.StreamUnsupported:
                    # upgrade-less upstream: the one subscription is a
                    # JSON long-poll session instead, same contract
                    self._json_poll_session()
                    continue
                with self._conn_lock:
                    self._sub_conn = conn
                ack = conn.subscribe(self._cursor, None,
                                     self._upstream_batch_s,
                                     timeout=10.0)
                if ack.get("epoch") != self._epoch \
                        or int(ack.get("seq") or 0) < self._cursor:
                    # upstream restarted without durability (fresh
                    # epoch / regressed seq space): everything we hold
                    # is from another life
                    self._sync()
                    continue
                warned = False
                while not self._stop.is_set():
                    out = conn.read_push(timeout=30.0)
                    if out is None:
                        continue  # liveness PING
                    if not self._apply(out):
                        break  # resynced; resubscribe at the new cursor
            except (ConnectionError, OSError) as e:
                if not self._stop.is_set() and not warned:
                    warned = True
                    logger.warning(
                        "proxy %s upstream subscription lost (%s); "
                        "reconnecting", self.name, e)
                self._stop.wait(0.2)
            finally:
                with self._conn_lock:
                    self._sub_conn = None
                if conn is not None:
                    conn.close()

    def _json_poll_session(self) -> None:
        """The negotiated-down upstream consumer: long-poll /watch and
        feed batches through the same `_apply` path the stream wire
        uses. Returns only on stop; transport faults propagate to the
        outer loop's backoff."""
        while not self._stop.is_set():
            status, doc = self._upstream.forward(
                "GET", f"/watch?since={self._cursor}&timeout=5")
            if status != 200 or not isinstance(doc, dict):
                raise ConnectionError(
                    f"upstream watch poll answered HTTP {status}")
            self._apply(doc)

    # ---- lifecycle ---------------------------------------------------------

    @property
    def event_log(self) -> _EventLog:
        """The downstream window (encode-once accounting, fake
        subscribers) — same attribute the apiserver's transport
        exposes as ``server.event_log``."""
        return self._log

    def downstream_watchers(self) -> int:
        return self._log.stream_subscriber_count()

    def stop(self) -> None:
        """Full teardown: downstream server (pump + subscriber writer
        threads joined, sockets severed), upstream subscription, and
        the upstream client's keep-alive sockets."""
        self._stop.set()
        self._server.shutdown()
        with self._conn_lock:
            conn = self._sub_conn
        if conn is not None:
            # wake the consumer blocked in read_push NOW, not at its
            # 30 s timeout
            conn.close()
        self._thread.join(timeout=10.0)
        self._upstream.close()

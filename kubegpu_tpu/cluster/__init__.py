"""In-process cluster substrate: the API-server fake and end-to-end wiring.

The Kubernetes API server is the only transport between components
(SURVEY.md §1) — annotations on Node/Pod objects are the wire protocol — so
an in-memory implementation of that narrow surface lets the whole framework
run and be tested without a cluster, exactly as the reference tests itself
with constructed NodeInfo/PodInfo structs (SURVEY.md §5).
"""

from kubegpu_tpu.cluster.apiserver import InMemoryAPIServer  # noqa: F401

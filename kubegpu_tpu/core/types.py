"""Core data model shared by every layer.

These are the TPU-native equivalents of the reference's L1 types
(`types/types.go:3-112`). Resource lists are plain ``dict[str, int]`` keyed
by hierarchical resource-path strings (see `kubegpu_tpu.core.grammar`) —
the string grammar is the wire format, carried in node/pod annotations.

Scorer selection rides per-resource as a small int enum
(reference: `device-scheduler/types/types.go:32-36`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator

# Namespace prefix for group resources (reference: `types/types.go:5-8`).
# Everything under this prefix is handled by the group allocator; everything
# else is "prechecked" — assumed handled by the core scheduler's ordinary
# resource accounting (reference: `resource/resourcetranslate.go:97-99`).
DEVICE_GROUP_PREFIX = "alpha/grpresource"

# A resource path -> requested/available amount.
ResourceList = dict  # dict[str, int]
# A request path -> the physical device path it is satisfied from.
ResourceLocation = dict  # dict[str, str]
# A resource path -> scorer enum (see kubegpu_tpu.allocator.scorers).
ResourceScorer = dict  # dict[str, int]


@dataclass
class ContainerInfo:
    """Per-container device requests and (after scheduling) the allocation.

    Reference: `types/types.go:19-25`.

    - ``kube_requests``: requests handled by the core scheduler (CPU/memory);
      kept only for resource translation, never serialized.
    - ``requests``: device requests as specified in pod annotations.
    - ``dev_requests``: requests after topology translation — what the group
      allocator actually schedules.
    - ``allocate_from``: request path -> physical device path; the scheduler's
      decision, and the only thing the runtime hook trusts.
    - ``scorer``: per-resource scorer overrides from the pod spec.
    """

    kube_requests: ResourceList = field(default_factory=dict)
    requests: ResourceList = field(default_factory=dict)
    dev_requests: ResourceList = field(default_factory=dict)
    allocate_from: ResourceLocation = field(default_factory=dict)
    scorer: ResourceScorer = field(default_factory=dict)

    def clone(self) -> "ContainerInfo":
        return ContainerInfo(
            kube_requests=dict(self.kube_requests),
            requests=dict(self.requests),
            dev_requests=dict(self.dev_requests),
            allocate_from=dict(self.allocate_from),
            scorer=dict(self.scorer),
        )

    # Wire format mirrors the reference's JSON tags (`types/types.go:19-25`)
    # so annotations are shape-compatible.
    def to_json(self) -> dict:
        out: dict = {}
        if self.requests:
            out["requests"] = dict(self.requests)
        if self.dev_requests:
            out["devrequests"] = dict(self.dev_requests)
        if self.allocate_from:
            out["allocatefrom"] = dict(self.allocate_from)
        if self.scorer:
            out["scorer"] = dict(self.scorer)
        return out

    @classmethod
    def from_json(cls, data: dict) -> "ContainerInfo":
        return cls(
            requests=dict(data.get("requests") or {}),
            dev_requests=dict(data.get("devrequests") or {}),
            allocate_from=dict(data.get("allocatefrom") or {}),
            scorer=dict(data.get("scorer") or {}),
        )


@dataclass
class PodInfo:
    """Pod-level view the device scheduler operates on.

    Reference: `types/types.go:51-57`. ``node_name`` is the node for which
    ``dev_requests``/``allocate_from`` are valid — set when the scheduler
    customizes the pod for a host, cleared when requests are invalidated.
    """

    name: str = ""
    node_name: str = ""
    requests: ResourceList = field(default_factory=dict)
    init_containers: dict = field(default_factory=dict)  # name -> ContainerInfo
    running_containers: dict = field(default_factory=dict)  # name -> ContainerInfo

    def container(self, name: str) -> "ContainerInfo | None":
        if name in self.init_containers:
            return self.init_containers[name]
        return self.running_containers.get(name)

    def all_containers(self) -> "Iterator[tuple[str, ContainerInfo, bool]]":
        """(name, info, is_init) triples, deterministic order."""
        for name in sorted(self.running_containers):
            yield name, self.running_containers[name], False
        for name in sorted(self.init_containers):
            yield name, self.init_containers[name], True

    def clone(self) -> "PodInfo":
        return PodInfo(
            name=self.name,
            node_name=self.node_name,
            requests=dict(self.requests),
            init_containers={k: v.clone() for k, v in self.init_containers.items()},
            running_containers={k: v.clone() for k, v in self.running_containers.items()},
        )

    def to_json(self) -> dict:
        out: dict = {}
        if self.name:
            out["podname"] = self.name
        if self.node_name:
            out["nodename"] = self.node_name
        if self.requests:
            out["requests"] = dict(self.requests)
        if self.init_containers:
            out["initcontainer"] = {k: v.to_json() for k, v in self.init_containers.items()}
        if self.running_containers:
            out["runningcontainer"] = {
                k: v.to_json() for k, v in self.running_containers.items()
            }
        return out

    @classmethod
    def from_json(cls, data: dict) -> "PodInfo":
        return cls(
            name=data.get("podname", ""),
            node_name=data.get("nodename", ""),
            requests=dict(data.get("requests") or {}),
            init_containers={
                k: ContainerInfo.from_json(v)
                for k, v in (data.get("initcontainer") or {}).items()
            },
            running_containers={
                k: ContainerInfo.from_json(v)
                for k, v in (data.get("runningcontainer") or {}).items()
            },
        )


@dataclass
class NodeInfo:
    """Device inventory a node advertises, plus scheduler-side usage.

    Reference: `types/types.go:76-82`. ``used`` is scheduler-side state —
    the advertiser never writes it, and the annotation decoder preserves the
    in-memory value across re-patches (`kubeinterface.go:54-58`).
    """

    name: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    used: ResourceList = field(default_factory=dict)
    scorer: ResourceScorer = field(default_factory=dict)

    def clone(self) -> "NodeInfo":
        return NodeInfo(
            name=self.name,
            capacity=dict(self.capacity),
            allocatable=dict(self.allocatable),
            used=dict(self.used),
            scorer=dict(self.scorer),
        )

    def shape_key(self) -> tuple:
        """Hashable fingerprint of everything a fit decision reads —
        inventory, usage, scorer config — deliberately excluding ``name``:
        two nodes with equal shape keys give identical (fits, reasons,
        score) for the same request, which is what lets a uniform fleet
        share one allocator search (the reference's tree-shape cluster
        cache idea, `gpu.go:102-183`, applied to the fit pass)."""
        # zero used-entries are accounting residue (take then return):
        # a churned node must shape-match a fresh one
        return (tuple(sorted(self.allocatable.items())),
                tuple(sorted((k, v) for k, v in self.used.items() if v)),
                tuple(sorted(self.scorer.items())))

    def to_json(self) -> dict:
        out: dict = {}
        if self.name:
            out["name"] = self.name
        if self.capacity:
            out["capacity"] = dict(self.capacity)
        if self.allocatable:
            out["allocatable"] = dict(self.allocatable)
        if self.used:
            out["used"] = dict(self.used)
        if self.scorer:
            out["scorer"] = dict(self.scorer)
        return out

    @classmethod
    def from_json(cls, data: dict) -> "NodeInfo":
        return cls(
            name=data.get("name", ""),
            capacity=dict(data.get("capacity") or {}),
            allocatable=dict(data.get("allocatable") or {}),
            used=dict(data.get("used") or {}),
            scorer=dict(data.get("scorer") or {}),
        )


def add_group_resource(res: ResourceList, key: str, val: int) -> None:
    """Add an amount under the group-resource prefix.

    Reference: `types/types.go:114-116`.
    """
    res[f"{DEVICE_GROUP_PREFIX}/{key}"] = val

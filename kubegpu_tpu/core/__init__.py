"""L1: shared types, the TPU resource-name grammar, and the annotation codec."""

from kubegpu_tpu.core.types import (  # noqa: F401
    DEVICE_GROUP_PREFIX,
    ContainerInfo,
    NodeInfo,
    PodInfo,
)

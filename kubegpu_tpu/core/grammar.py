"""The TPU resource-name grammar — the system's de-facto data model.

Mirrors the reference's grammar (`SURVEY.md` §4; reference
`nvidia_gpu_manager.go:104-106,216-219`) with TPU semantics:

- Group resources live under ``alpha/grpresource/<path>``.
- Chip leaves::

      alpha/grpresource/.../tpu/<chip-id>/chips   = 1
      alpha/grpresource/.../tpu/<chip-id>/hbm     = bytes
      alpha/grpresource/.../tpu/<chip-id>/enumLinks = ICI link-direction bitmask

- Topology levels are prepended at discovery time, innermost first::

      alpha/grpresource/tpugrp1/<i>/tpugrp0/<j>/tpu/<chip-id>/chips

  ``tpugrp0`` groups chips that share a direct ICI neighborhood (e.g. a
  2x2x1 sub-cube / tray); ``tpugrp1`` groups trays that share a host (the
  DCN boundary).  This replaces the reference's NVLink P2P link-level
  grouping (`nvidia_gpu_manager.go:93-121`).

- Any leaf segment starting with ``enum`` is a bitmask resource matched by
  the enum scorer (`resource/resourcetranslate.go:20-27`).

- Chip ids encode ICI mesh coordinates: ``x.y.z`` (e.g. ``0.1.3``), so the
  contiguity predicate can recover coordinates from the wire format alone.

Pod-level knobs (in the pod annotation's ``requests``):

- ``alpha.tpu/numchips``: flat chip count, translated into per-chip group
  requests (analogue of ``alpha.gpu/numgpu``, `gpuplugintypes/types.go:7`).
- ``alpha.tpu/hbm-per-chip``: optional minimum HBM bytes per requested chip.
- ``alpha.tpu/tpu-generate-topology``: 0 = translate requests as-is;
  1 = rewrite to the best-shaped inventory tree in the cluster
  (analogue of ``alpha.gpu/gpu-generate-topology``, `gpu_scheduler.go:13-16`).
"""

from __future__ import annotations

import re

from kubegpu_tpu.core.types import DEVICE_GROUP_PREFIX

# ---- leaf vocabulary -------------------------------------------------------

TPU_LEAF = "tpu"          # the device level, like "gpu" in the reference
CHIPS_SUFFIX = "chips"    # 1 per chip, like "cards"
HBM_SUFFIX = "hbm"        # bytes of HBM, like "memory"
LINKS_SUFFIX = "enumLinks"  # ICI link-direction bitmask (enum resource)

# ---- topology levels (innermost -> outermost) ------------------------------

TPU_GRP_STEM = "tpugrp"          # level names are <stem><level-number>
TPU_GRP0 = f"{TPU_GRP_STEM}0"    # direct ICI neighborhood (tray / sub-cube)
TPU_GRP1 = f"{TPU_GRP_STEM}1"    # host / DCN boundary
TOPOLOGY_LEVELS = (TPU_GRP0, TPU_GRP1)

# ---- pod-level request names ----------------------------------------------

RESOURCE_NUM_CHIPS = "alpha.tpu/numchips"
RESOURCE_HBM_PER_CHIP = "alpha.tpu/hbm-per-chip"
TPU_TOPOLOGY_GENERATION = "alpha.tpu/tpu-generate-topology"

_ENUM_RE = re.compile(r"\S*/(\S*)")
_CHIP_FROM_PATH_RE = re.compile(rf".*/{TPU_LEAF}/([^/]+)/{CHIPS_SUFFIX}$")


def is_group_resource(name: str) -> bool:
    """True if the name is handled by the group allocator.

    Reference: `resource/resourcetranslate.go:15-17`.
    """
    return name.startswith(DEVICE_GROUP_PREFIX)


def prechecked_resource(name: str) -> bool:
    """Resources outside the group prefix are the core scheduler's problem.

    Reference: `resource/resourcetranslate.go:97-99`.
    """
    return not is_group_resource(name)


def is_enum_resource(name: str) -> bool:
    """Leaf segments starting with ``enum`` are bitmask-typed.

    Reference: `resource/resourcetranslate.go:20-27`.
    """
    m = _ENUM_RE.match(name)
    if m:
        return m.group(1).lower().startswith("enum")
    return False


def chip_resource(chip_id: str, suffix: str, *levels: tuple) -> str:
    """Build a full group-resource path for one chip attribute.

    ``levels`` are (level_name, index) pairs, outermost first, e.g.
    ``chip_resource("0.0.0", "chips", ("tpugrp1", 0), ("tpugrp0", 1))`` ->
    ``alpha/grpresource/tpugrp1/0/tpugrp0/1/tpu/0.0.0/chips``.
    """
    parts = [DEVICE_GROUP_PREFIX]
    for name, idx in levels:
        parts.append(f"{name}/{idx}")
    parts.append(f"{TPU_LEAF}/{chip_id}/{suffix}")
    return "/".join(parts)


def chip_id_from_path(path: str) -> str | None:
    """Extract the chip id from a ``.../tpu/<chip-id>/chips`` path.

    This is what the runtime hook uses to turn ``allocate_from`` values into
    ``TPU_VISIBLE_CHIPS`` (reference analogue: UUID regex extraction,
    `nvidia_gpu_manager.go:238-253`).
    """
    m = _CHIP_FROM_PATH_RE.match(path)
    return m.group(1) if m else None


def chip_prefix_from_path(path: str) -> str | None:
    """The ``.../tpu/<chip-id>`` prefix of a chips-leaf path, or None.

    The gang preemption planner keys chip OWNERSHIP by this prefix: a
    bound pod's ``allocate_from`` values name the same prefixes the node
    advertises, so (node, prefix) identifies a physical chip."""
    if chip_id_from_path(path) is None:
        return None
    return path[: path.rfind("/")]


def coords_from_chip_id(chip_id: str) -> tuple | None:
    """Chip ids encode mesh coordinates as dot-separated ints, e.g. ``1.0.3``."""
    parts = chip_id.split(".")
    try:
        return tuple(int(p) for p in parts)
    except ValueError:
        return None


def chip_id_from_coords(coords: "tuple | list") -> str:
    return ".".join(str(int(c)) for c in coords)

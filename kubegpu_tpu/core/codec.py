"""Annotation codec: node/pod annotations <-> L1 types.

The Kubernetes API server is the only channel between the scheduler and the
node (`SURVEY.md` §1): the node advertises its device inventory as a single
JSON blob under ``node.alpha/DeviceInformation`` and the scheduler writes
the allocation back as ``pod.alpha/DeviceInformation``. Pod annotations
*are* the wire protocol.

Reference: `kubeinterface/kubeinterface.go:29-123`. Kubernetes objects are
handled as plain dicts in their JSON shape (``{"metadata": {...},
"spec": {...}}``) so the codec works against any client or a test fake.
"""

from __future__ import annotations

import json
import math
import re
import struct

from kubegpu_tpu.core.types import ContainerInfo, NodeInfo, PodInfo

NODE_ANNOTATION_KEY = "node.alpha/DeviceInformation"
POD_ANNOTATION_KEY = "pod.alpha/DeviceInformation"
# Routable address of the node agent's host, advertised alongside the
# inventory. The runtime hook resolves a gang's coordinator node through
# this when building TPU_COORDINATOR_ADDRESS (node NAMES are cluster
# identifiers, not necessarily resolvable hostnames).
NODE_ADDRESS_ANNOTATION = "node.alpha/Address"
# Wall-clock timestamp (seconds) the advertiser stamps on every successful
# pass — the liveness signal the scheduler-side NodeLifecycle controller
# ages into Ready/Stale/Lost. Wall clock, not monotonic: the stamp crosses
# process (and potentially host) boundaries.
NODE_HEARTBEAT_ANNOTATION = "node.alpha/Heartbeat"
# Per-chip health map {chip_id: "healthy" | "degraded" | ...} reported by
# the device backend. A non-healthy chip is withheld from the advertised
# allocatable inventory (the node shrinks, it does not vanish).
NODE_CHIP_HEALTH_ANNOTATION = "node.alpha/ChipHealth"
# Per-chip ICI link health {chip_id: dead-direction bitmask}, bit i set
# when the link toward ``topology.mesh.LINK_DIRS[i]`` is down. The
# advertiser clears dead bits from the advertised ``enumLinks`` masks
# (so the mesh search routes around them) and stamps the raw map here so
# the repair controller can tell a dead link from a mesh edge.
NODE_LINK_HEALTH_ANNOTATION = "node.alpha/LinkHealth"

# Kubernetes quantity suffixes -> multiplier. Serialized pods carry requests
# as quantity strings ("500m", "1Gi"); the reference reads them through
# resource.Quantity.Value(), which rounds up to a whole int64.
_QUANTITY_SUFFIXES = {
    "": 1,
    "m": 1e-3,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}
_QUANTITY_RE = re.compile(r"^([+-]?[0-9.eE+-]+?)([A-Za-z]*)$")
_F64 = struct.Struct("<d")


def parse_quantity(val: "int | float | str") -> int:
    """Parse a Kubernetes resource quantity to a whole number, rounding up.

    Accepts ints/floats directly and strings like ``"2"``, ``"500m"``,
    ``"1Ki"``, ``"1Gi"``, ``"1e3"``. Mirrors ``resource.Quantity.Value()``
    semantics (round up), so ``"500m"`` -> 1.
    """
    if isinstance(val, (int, float)):
        return math.ceil(val)
    m = _QUANTITY_RE.match(str(val).strip())
    if not m or m.group(2) not in _QUANTITY_SUFFIXES:
        raise ValueError(f"invalid quantity: {val!r}")
    number, suffix = m.groups()
    try:
        parsed = float(number)
    except ValueError:
        raise ValueError(f"invalid quantity: {val!r}") from None
    return math.ceil(parsed * _QUANTITY_SUFFIXES[suffix])


def _annotations(meta: dict) -> dict:
    # Tolerate "annotations": null, which some serializers emit for empty maps.
    if not meta.get("annotations"):
        meta["annotations"] = {}
    return meta["annotations"]


def node_info_to_annotation(meta: dict, node_info: NodeInfo) -> None:
    """Serialize a node's device inventory into its metadata annotations.

    Used by the device advertiser (`kubeinterface.go:29-40`).
    """
    _annotations(meta)[NODE_ANNOTATION_KEY] = json.dumps(
        node_info.to_json(), sort_keys=True
    )


def annotation_to_node_info(meta: dict, existing: NodeInfo | None = None) -> NodeInfo:
    """Decode a node annotation, preserving in-memory ``used`` accounting.

    The advertiser never writes ``used``; the scheduler's view of usage must
    survive inventory re-patches (`kubeinterface.go:42-61`).
    """
    node_info = NodeInfo()
    ann = meta.get("annotations") or {}
    raw = ann.get(NODE_ANNOTATION_KEY)
    if raw is not None:
        node_info = NodeInfo.from_json(json.loads(raw))
    if existing is not None and existing.used:
        for key, val in existing.used.items():
            node_info.used[key] = val
    return node_info


def heartbeat_to_annotation(meta: dict, timestamp: float) -> None:
    """Stamp the advertiser's liveness heartbeat (wall-clock seconds)."""
    _annotations(meta)[NODE_HEARTBEAT_ANNOTATION] = json.dumps(
        round(float(timestamp), 3))


def annotation_to_heartbeat(meta: dict) -> float | None:
    """Decode the heartbeat timestamp; None = no (or unparseable)
    heartbeat, meaning liveness is not tracked for this node (a node
    registered out-of-band, or an older advertiser)."""
    raw = (meta.get("annotations") or {}).get(NODE_HEARTBEAT_ANNOTATION)
    if raw is None:
        return None
    try:
        return float(json.loads(raw))
    except (TypeError, ValueError):
        return None


def chip_health_to_annotation(meta: dict, health: dict) -> None:
    """Serialize the backend's per-chip health map."""
    _annotations(meta)[NODE_CHIP_HEALTH_ANNOTATION] = json.dumps(
        dict(health), sort_keys=True)


def annotation_to_chip_health(meta: dict) -> dict:
    """Decode the per-chip health map; {} = everything healthy."""
    raw = (meta.get("annotations") or {}).get(NODE_CHIP_HEALTH_ANNOTATION)
    if not raw:
        return {}
    try:
        decoded = json.loads(raw)
    except (TypeError, ValueError):
        return {}
    return decoded if isinstance(decoded, dict) else {}


def link_health_to_annotation(meta: dict, dead_links: dict) -> None:
    """Serialize the backend's per-chip dead-link bitmask map. Zero
    masks are dropped — absence means every link up."""
    _annotations(meta)[NODE_LINK_HEALTH_ANNOTATION] = json.dumps(
        {k: int(v) for k, v in dict(dead_links).items() if int(v)},
        sort_keys=True)


def annotation_to_link_health(meta: dict) -> dict:
    """Decode the per-chip dead-link map; {} = every link up."""
    raw = (meta.get("annotations") or {}).get(NODE_LINK_HEALTH_ANNOTATION)
    if not raw:
        return {}
    try:
        decoded = json.loads(raw)
    except (TypeError, ValueError):
        return {}
    if not isinstance(decoded, dict):
        return {}
    out = {}
    for chip_id, mask in decoded.items():
        try:
            if int(mask):
                out[str(chip_id)] = int(mask)
        except (TypeError, ValueError):
            continue
    return out


def pod_info_to_annotation(meta: dict, pod_info: PodInfo) -> None:
    """Serialize the scheduler's decision into pod metadata annotations.

    Reference: `kubeinterface.go:111-123`.
    """
    _annotations(meta)[POD_ANNOTATION_KEY] = json.dumps(
        pod_info.to_json(), sort_keys=True
    )


def annotation_to_pod_info(meta: dict) -> PodInfo:
    """Decode the scheduler's persisted decision from pod metadata, raw —
    no pod-spec merge, no invalidation. This is the read-back half of
    :func:`pod_info_to_annotation`; consumers evaluating a pod against a
    spec should go through :func:`kube_pod_to_pod_info`, which folds the
    container requests in on top."""
    raw = (meta.get("annotations") or {}).get(POD_ANNOTATION_KEY)
    if raw is None:
        return PodInfo()
    return PodInfo.from_json(json.loads(raw))


def _merge_kube_containers(
    containers: dict, kube_containers: list, invalidate: bool
) -> None:
    """Fold core-Kubernetes container requests into ContainerInfos.

    Reference: `kubeinterface.go:63-85`. When ``invalidate`` is set, any
    stale scheduler output (``allocate_from``/``dev_requests``) is discarded
    and ``dev_requests`` reset to the annotation-specified ``requests`` so a
    fresh scheduling pass starts from intent, not history.
    """
    for c in kube_containers:
        name = c["name"]
        info = containers.setdefault(name, ContainerInfo())
        for res, val in ((c.get("resources") or {}).get("requests") or {}).items():
            info.kube_requests[res] = parse_quantity(val)
    if invalidate:
        for info in containers.values():
            info.allocate_from = {}
            info.dev_requests = dict(info.requests)


# ---- binary wire codec ------------------------------------------------------
# The compact encoding the streaming transport (cluster/stream.py) frames
# carry: a tagged value format for the JSON-shaped control-plane records
# (pods, node snapshots, watch deltas, requests/responses) with string
# interning. Two interning layers:
#
#   * a STATIC table of protocol constants (object keys, verbs, event
#     types, the annotation keys) shared by both ends — these never cost
#     more than a 1-2 byte reference on the wire;
#   * a DYNAMIC per-frame table: the first occurrence of any other
#     string inside one frame is sent inline and assigned the next id,
#     every repeat is a reference. Pod/node/class names repeat heavily
#     inside a coalesced watch batch or a bind_many body, which is where
#     the bytes are.
#
# The dynamic table is scoped to ONE frame on purpose: every frame
# decodes standalone, so the server can encode a watch batch once and
# fan the identical bytes out to every subscriber regardless of when
# each subscribed, and a reconnect never has interner state to resync.

_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR_NEW = 0x05   # inline utf-8, registers the next dynamic id
_T_STR_REF = 0x06   # varint reference into static+dynamic table
_T_LIST = 0x07
_T_DICT = 0x08

_STATIC_STRINGS: "tuple[str, ...]" = (
    # object shape
    "metadata", "name", "annotations", "labels", "spec", "status",
    "nodeName", "containers", "initContainers", "resources", "requests",
    "allocatable", "capacity", "cpu", "pods", "priority", "phase",
    "volumes", "persistentVolumeClaim", "claimName", "volumeName",
    "storageClassName", "nodeAffinity",
    # annotation keys (the hot per-record payloads)
    NODE_ANNOTATION_KEY, POD_ANNOTATION_KEY, NODE_ADDRESS_ANNOTATION,
    NODE_HEARTBEAT_ANNOTATION, NODE_CHIP_HEALTH_ANNOTATION,
    # verbs + routes
    "GET", "POST", "PUT", "PATCH", "DELETE",
    # watch stream
    "node", "pod", "pv", "pvc", "added", "modified", "deleted",
    "events", "seq", "coalesced", "relist", "epoch", "items",
    # error detail
    "error", "per_pod", "bindings", "holder", "ttl",
    # multi-tenant front door (appended last: static ids are wire
    # protocol, so existing indexes must never shift)
    "retry_after_s", "tenant", "kgtpu.io/tenant", "quota", "weight",
    "hard_chips", "chips_created",
    # device-fault repair (appended last — same shift rule as above)
    NODE_LINK_HEALTH_ANNOTATION,
)
_STATIC_INDEX = {s: i for i, s in enumerate(_STATIC_STRINGS)}


class CodecError(ValueError):
    """Malformed binary payload: truncated, bad tag, or a dangling
    intern reference. Raised by every decode_* function — a transport
    must treat it as a poisoned frame, never retry the bytes."""


# Both varint halves share one magnitude cap (1024 bits — far beyond any
# control-plane quantity, tight enough that hostile frames cannot force
# quadratic bigint work): the ENCODER refuses what the decoder would
# reject, so the wire never carries a frame only one side understands.
_VARINT_MAX_BITS = 1024


def _encode_varint(buf: bytearray, n: int) -> None:
    if n.bit_length() > _VARINT_MAX_BITS:
        raise CodecError(f"integer too large for the wire "
                         f"({n.bit_length()} bits > {_VARINT_MAX_BITS})")
    while n > 0x7F:
        buf.append((n & 0x7F) | 0x80)
        n >>= 7
    buf.append(n)


def _encode_into(buf: bytearray, obj: object, table: "dict[str, int]") -> None:
    # Hot path: type checks ordered by frequency (strings dominate the
    # control-plane records), one-byte varints inlined.
    t = type(obj)
    if t is str:
        idx = table.get(obj)
        if idx is not None:
            if idx < 0x80:
                buf.append(_T_STR_REF)
                buf.append(idx)
            else:
                buf.append(_T_STR_REF)
                _encode_varint(buf, idx)
        else:
            table[obj] = len(table)  # type: ignore[index]
            raw = obj.encode()  # type: ignore[union-attr]
            buf.append(_T_STR_NEW)
            n = len(raw)
            if n < 0x80:
                buf.append(n)
            else:
                _encode_varint(buf, n)
            buf += raw
    elif t is dict:
        buf.append(_T_DICT)
        _encode_varint(buf, len(obj))  # type: ignore[arg-type]
        for key, val in obj.items():  # type: ignore[union-attr]
            _encode_into(buf, key, table)
            _encode_into(buf, val, table)
    elif t is int:
        buf.append(_T_INT)
        # zigzag: sign rides the low bit so magnitudes stay short
        zz = (obj << 1) if obj >= 0 else ((-obj) << 1) - 1  # type: ignore
        if zz < 0x80:
            buf.append(zz)
        else:
            _encode_varint(buf, zz)
    elif t is list or t is tuple:
        buf.append(_T_LIST)
        _encode_varint(buf, len(obj))  # type: ignore[arg-type]
        for item in obj:  # type: ignore[union-attr]
            _encode_into(buf, item, table)
    elif obj is None:
        buf.append(_T_NONE)
    elif obj is True:
        buf.append(_T_TRUE)
    elif obj is False:
        buf.append(_T_FALSE)
    elif t is float:
        buf.append(_T_FLOAT)
        buf += _F64.pack(obj)  # type: ignore[arg-type]
    else:
        # slow path: subclasses coerce to their exact base type and
        # re-enter the fast path above — ONE copy of every encoding;
        # anything else falls back to str, like the WAL's json default
        _encode_into(buf, _coerce(obj), table)


def _coerce(obj: object) -> object:
    if isinstance(obj, bool):
        return bool(obj)
    if isinstance(obj, str):
        return str(obj)
    if isinstance(obj, int):
        return int(obj)
    if isinstance(obj, float):
        return float(obj)
    if isinstance(obj, (list, tuple)):
        return list(obj)
    if isinstance(obj, dict):
        return dict(obj)
    return str(obj)


def _decode_varint(data: bytes, pos: int) -> "tuple[int, int]":
    out = 0
    shift = 0
    end = len(data)
    while True:
        if pos >= end:
            raise CodecError("truncated varint")
        byte = data[pos]
        pos += 1
        out |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return out, pos
        shift += 7
        if shift > _VARINT_MAX_BITS:
            raise CodecError("varint too long")


def _decode_from(data: bytes, pos: int,
                 table: "list[str]") -> "tuple[object, int]":
    # Hot path (every watch delta and response decodes through here):
    # branches ordered by frequency, one-byte varints inlined. Index
    # errors from truncation surface as IndexError and are wrapped into
    # CodecError by the public entry points.
    end = len(data)
    if pos >= end:
        raise CodecError("truncated value")
    tag = data[pos]
    pos += 1
    if tag == _T_STR_REF:
        idx = data[pos]
        pos += 1
        if idx & 0x80:
            idx, pos = _decode_varint(data, pos - 1)
        if idx >= len(table):
            raise CodecError(f"dangling string reference {idx}")
        return table[idx], pos
    if tag == _T_STR_NEW:
        n = data[pos]
        pos += 1
        if n & 0x80:
            n, pos = _decode_varint(data, pos - 1)
        if pos + n > end:
            raise CodecError("truncated string")
        s = data[pos:pos + n].decode()
        table.append(s)
        return s, pos + n
    if tag == _T_DICT:
        n = data[pos]
        pos += 1
        if n & 0x80:
            n, pos = _decode_varint(data, pos - 1)
        if n > end - pos:
            raise CodecError("dict longer than payload")
        out_d: "dict[object, object]" = {}
        for _ in range(n):
            key, pos = _decode_from(data, pos, table)
            val, pos = _decode_from(data, pos, table)
            out_d[key] = val
        return out_d, pos
    if tag == _T_LIST:
        n = data[pos]
        pos += 1
        if n & 0x80:
            n, pos = _decode_varint(data, pos - 1)
        if n > end - pos:
            raise CodecError("list longer than payload")
        out_l: "list[object]" = []
        append = out_l.append
        for _ in range(n):
            item, pos = _decode_from(data, pos, table)
            append(item)
        return out_l, pos
    if tag == _T_INT:
        zz = data[pos]
        pos += 1
        if zz & 0x80:
            zz, pos = _decode_varint(data, pos - 1)
        return (zz >> 1) ^ -(zz & 1), pos
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_FLOAT:
        if pos + 8 > end:
            raise CodecError("truncated float")
        return _F64.unpack_from(data, pos)[0], pos + 8
    raise CodecError(f"unknown tag 0x{tag:02x}")


def encode_value(obj: object) -> bytes:
    """Encode one JSON-shaped value (the generic wire payload)."""
    buf = bytearray()
    _encode_into(buf, obj, dict(_STATIC_INDEX))
    return bytes(buf)


def decode_value(data: bytes) -> object:
    """Decode one value; raises :class:`CodecError` on malformed bytes
    (truncation, bad tags, dangling intern references) and rejects
    trailing garbage — a frame is exactly one value."""
    try:
        val, pos = _decode_from(data, 0, list(_STATIC_STRINGS))
    except IndexError:
        raise CodecError("truncated value") from None
    except RecursionError:
        raise CodecError("value nested too deeply") from None
    except UnicodeDecodeError:
        raise CodecError("string payload is not valid utf-8") from None
    except TypeError:
        # e.g. a decoded list arriving in dict-key position
        raise CodecError("unhashable dict key in payload") from None
    if pos != len(data):
        raise CodecError(f"{len(data) - pos} trailing byte(s) after value")
    return val


def _expect_dict(val: object, what: str) -> dict:
    if not isinstance(val, dict):
        raise CodecError(f"{what}: expected an object, got "
                         f"{type(val).__name__}")
    return val


def encode_pod(pod: dict) -> bytes:
    """Compact encoding of one pod object (JSON dict shape)."""
    return encode_value(pod)


def decode_pod(data: bytes) -> dict:
    return _expect_dict(decode_value(data), "pod record")


def encode_node_snapshot(node: dict) -> bytes:
    """Compact encoding of one node object, device annotation included —
    the advertiser re-patch / watch payload, where repeated chip-class
    names are what interning folds away."""
    return encode_value(node)


def decode_node_snapshot(data: bytes) -> dict:
    return _expect_dict(decode_value(data), "node snapshot")


def encode_watch_batch(events: "list[tuple]", seq: int, coalesced: int = 0,
                       relist: bool = False, epoch: "str | None" = None,
                       ts: float = 0.0) -> bytes:
    """One coalesced watch window ``[(seq, kind, event, obj), ...]`` plus
    its resume cursor — encoded ONCE; the event-log fan-out writes the
    same bytes to every subscribed watcher. ``ts`` is the sender's
    wall-clock stamp (cross-process, so not monotonic) backing
    ``watch_push_lag_ms``."""
    return encode_value([[list(e) for e in events], seq, coalesced,
                         relist, epoch, ts])


def decode_watch_batch(data: bytes) -> dict:
    val = decode_value(data)
    if not isinstance(val, list) or len(val) != 6 or \
            not isinstance(val[0], list):
        raise CodecError("malformed watch batch")
    events = []
    for ev in val[0]:
        if not isinstance(ev, list) or len(ev) != 4:
            raise CodecError("malformed watch event")
        events.append(tuple(ev))
    return {"events": events, "seq": val[1], "coalesced": val[2],
            "relist": bool(val[3]), "epoch": val[4], "ts": val[5]}


def encode_request(method: str, path: str, body: object,
                   trace: "str | None" = None) -> bytes:
    """One framed API request: verb + route + body + optional trace
    context (the X-KGTPU-Trace equivalent, riding the frame)."""
    return encode_value([method, path, body, trace])


def decode_request(data: bytes) -> "tuple[str, str, object, str | None]":
    val = decode_value(data)
    if not isinstance(val, list) or len(val) != 4 or \
            not isinstance(val[0], str) or not isinstance(val[1], str) or \
            not (val[3] is None or isinstance(val[3], str)):
        raise CodecError("malformed request frame")
    return val[0], val[1], val[2], val[3]


def encode_response(status: int, body: object) -> bytes:
    """One framed API response: HTTP-compatible status + body (error
    bodies carry the same ``{"error", "per_pod"}`` conflict/bind detail
    the JSON wire sends)."""
    return encode_value([status, body])


def decode_response(data: bytes) -> "tuple[int, object]":
    val = decode_value(data)
    if not isinstance(val, list) or len(val) != 2 or \
            not isinstance(val[0], int):
        raise CodecError("malformed response frame")
    return val[0], val[1]


def kube_pod_to_pod_info(kube_pod: dict, invalidate_existing: bool) -> PodInfo:
    """Convert a Kubernetes pod (JSON dict) into the scheduler's PodInfo.

    Reference: `kubeinterface.go:88-109`. Reads any existing
    ``pod.alpha/DeviceInformation`` annotation first, then merges the pod
    spec's container requests into ``kube_requests``.
    """
    meta = kube_pod.get("metadata") or {}
    pod_info = PodInfo()
    raw = (meta.get("annotations") or {}).get(POD_ANNOTATION_KEY)
    if raw is not None:
        pod_info = PodInfo.from_json(json.loads(raw))
    pod_info.name = meta.get("name", "")
    spec = kube_pod.get("spec") or {}
    _merge_kube_containers(
        pod_info.init_containers, spec.get("initContainers") or [], invalidate_existing
    )
    _merge_kube_containers(
        pod_info.running_containers, spec.get("containers") or [], invalidate_existing
    )
    if invalidate_existing:
        pod_info.node_name = ""
    return pod_info

"""Annotation codec: node/pod annotations <-> L1 types.

The Kubernetes API server is the only channel between the scheduler and the
node (`SURVEY.md` §1): the node advertises its device inventory as a single
JSON blob under ``node.alpha/DeviceInformation`` and the scheduler writes
the allocation back as ``pod.alpha/DeviceInformation``. Pod annotations
*are* the wire protocol.

Reference: `kubeinterface/kubeinterface.go:29-123`. Kubernetes objects are
handled as plain dicts in their JSON shape (``{"metadata": {...},
"spec": {...}}``) so the codec works against any client or a test fake.
"""

from __future__ import annotations

import json
import math
import re

from kubegpu_tpu.core.types import ContainerInfo, NodeInfo, PodInfo

NODE_ANNOTATION_KEY = "node.alpha/DeviceInformation"
POD_ANNOTATION_KEY = "pod.alpha/DeviceInformation"
# Routable address of the node agent's host, advertised alongside the
# inventory. The runtime hook resolves a gang's coordinator node through
# this when building TPU_COORDINATOR_ADDRESS (node NAMES are cluster
# identifiers, not necessarily resolvable hostnames).
NODE_ADDRESS_ANNOTATION = "node.alpha/Address"
# Wall-clock timestamp (seconds) the advertiser stamps on every successful
# pass — the liveness signal the scheduler-side NodeLifecycle controller
# ages into Ready/Stale/Lost. Wall clock, not monotonic: the stamp crosses
# process (and potentially host) boundaries.
NODE_HEARTBEAT_ANNOTATION = "node.alpha/Heartbeat"
# Per-chip health map {chip_id: "healthy" | "degraded" | ...} reported by
# the device backend. A non-healthy chip is withheld from the advertised
# allocatable inventory (the node shrinks, it does not vanish).
NODE_CHIP_HEALTH_ANNOTATION = "node.alpha/ChipHealth"

# Kubernetes quantity suffixes -> multiplier. Serialized pods carry requests
# as quantity strings ("500m", "1Gi"); the reference reads them through
# resource.Quantity.Value(), which rounds up to a whole int64.
_QUANTITY_SUFFIXES = {
    "": 1,
    "m": 1e-3,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}
_QUANTITY_RE = re.compile(r"^([+-]?[0-9.eE+-]+?)([A-Za-z]*)$")


def parse_quantity(val: "int | float | str") -> int:
    """Parse a Kubernetes resource quantity to a whole number, rounding up.

    Accepts ints/floats directly and strings like ``"2"``, ``"500m"``,
    ``"1Ki"``, ``"1Gi"``, ``"1e3"``. Mirrors ``resource.Quantity.Value()``
    semantics (round up), so ``"500m"`` -> 1.
    """
    if isinstance(val, (int, float)):
        return math.ceil(val)
    m = _QUANTITY_RE.match(str(val).strip())
    if not m or m.group(2) not in _QUANTITY_SUFFIXES:
        raise ValueError(f"invalid quantity: {val!r}")
    number, suffix = m.groups()
    try:
        parsed = float(number)
    except ValueError:
        raise ValueError(f"invalid quantity: {val!r}") from None
    return math.ceil(parsed * _QUANTITY_SUFFIXES[suffix])


def _annotations(meta: dict) -> dict:
    # Tolerate "annotations": null, which some serializers emit for empty maps.
    if not meta.get("annotations"):
        meta["annotations"] = {}
    return meta["annotations"]


def node_info_to_annotation(meta: dict, node_info: NodeInfo) -> None:
    """Serialize a node's device inventory into its metadata annotations.

    Used by the device advertiser (`kubeinterface.go:29-40`).
    """
    _annotations(meta)[NODE_ANNOTATION_KEY] = json.dumps(
        node_info.to_json(), sort_keys=True
    )


def annotation_to_node_info(meta: dict, existing: NodeInfo | None = None) -> NodeInfo:
    """Decode a node annotation, preserving in-memory ``used`` accounting.

    The advertiser never writes ``used``; the scheduler's view of usage must
    survive inventory re-patches (`kubeinterface.go:42-61`).
    """
    node_info = NodeInfo()
    ann = meta.get("annotations") or {}
    raw = ann.get(NODE_ANNOTATION_KEY)
    if raw is not None:
        node_info = NodeInfo.from_json(json.loads(raw))
    if existing is not None and existing.used:
        for key, val in existing.used.items():
            node_info.used[key] = val
    return node_info


def heartbeat_to_annotation(meta: dict, timestamp: float) -> None:
    """Stamp the advertiser's liveness heartbeat (wall-clock seconds)."""
    _annotations(meta)[NODE_HEARTBEAT_ANNOTATION] = json.dumps(
        round(float(timestamp), 3))


def annotation_to_heartbeat(meta: dict) -> float | None:
    """Decode the heartbeat timestamp; None = no (or unparseable)
    heartbeat, meaning liveness is not tracked for this node (a node
    registered out-of-band, or an older advertiser)."""
    raw = (meta.get("annotations") or {}).get(NODE_HEARTBEAT_ANNOTATION)
    if raw is None:
        return None
    try:
        return float(json.loads(raw))
    except (TypeError, ValueError):
        return None


def chip_health_to_annotation(meta: dict, health: dict) -> None:
    """Serialize the backend's per-chip health map."""
    _annotations(meta)[NODE_CHIP_HEALTH_ANNOTATION] = json.dumps(
        dict(health), sort_keys=True)


def annotation_to_chip_health(meta: dict) -> dict:
    """Decode the per-chip health map; {} = everything healthy."""
    raw = (meta.get("annotations") or {}).get(NODE_CHIP_HEALTH_ANNOTATION)
    if not raw:
        return {}
    try:
        decoded = json.loads(raw)
    except (TypeError, ValueError):
        return {}
    return decoded if isinstance(decoded, dict) else {}


def pod_info_to_annotation(meta: dict, pod_info: PodInfo) -> None:
    """Serialize the scheduler's decision into pod metadata annotations.

    Reference: `kubeinterface.go:111-123`.
    """
    _annotations(meta)[POD_ANNOTATION_KEY] = json.dumps(
        pod_info.to_json(), sort_keys=True
    )


def annotation_to_pod_info(meta: dict) -> PodInfo:
    """Decode the scheduler's persisted decision from pod metadata, raw —
    no pod-spec merge, no invalidation. This is the read-back half of
    :func:`pod_info_to_annotation`; consumers evaluating a pod against a
    spec should go through :func:`kube_pod_to_pod_info`, which folds the
    container requests in on top."""
    raw = (meta.get("annotations") or {}).get(POD_ANNOTATION_KEY)
    if raw is None:
        return PodInfo()
    return PodInfo.from_json(json.loads(raw))


def _merge_kube_containers(
    containers: dict, kube_containers: list, invalidate: bool
) -> None:
    """Fold core-Kubernetes container requests into ContainerInfos.

    Reference: `kubeinterface.go:63-85`. When ``invalidate`` is set, any
    stale scheduler output (``allocate_from``/``dev_requests``) is discarded
    and ``dev_requests`` reset to the annotation-specified ``requests`` so a
    fresh scheduling pass starts from intent, not history.
    """
    for c in kube_containers:
        name = c["name"]
        info = containers.setdefault(name, ContainerInfo())
        for res, val in ((c.get("resources") or {}).get("requests") or {}).items():
            info.kube_requests[res] = parse_quantity(val)
    if invalidate:
        for info in containers.values():
            info.allocate_from = {}
            info.dev_requests = dict(info.requests)


def kube_pod_to_pod_info(kube_pod: dict, invalidate_existing: bool) -> PodInfo:
    """Convert a Kubernetes pod (JSON dict) into the scheduler's PodInfo.

    Reference: `kubeinterface.go:88-109`. Reads any existing
    ``pod.alpha/DeviceInformation`` annotation first, then merges the pod
    spec's container requests into ``kube_requests``.
    """
    meta = kube_pod.get("metadata") or {}
    pod_info = PodInfo()
    raw = (meta.get("annotations") or {}).get(POD_ANNOTATION_KEY)
    if raw is not None:
        pod_info = PodInfo.from_json(json.loads(raw))
    pod_info.name = meta.get("name", "")
    spec = kube_pod.get("spec") or {}
    _merge_kube_containers(
        pod_info.init_containers, spec.get("initContainers") or [], invalidate_existing
    )
    _merge_kube_containers(
        pod_info.running_containers, spec.get("containers") or [], invalidate_existing
    )
    if invalidate_existing:
        pod_info.node_name = ""
    return pod_info

"""Recover ICI mesh structure from an advertised NodeInfo (or several).

The annotation wire format is the only channel between node and scheduler,
so everything placement needs must be derivable from it: chip coordinates
ride in chip ids, and torus wraparound is recovered from the advertised
``enumLinks`` bitmasks — a chip at the minimum coordinate of an axis that
still has the negative-direction link can only mean the axis wraps.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from kubegpu_tpu.core import grammar
from kubegpu_tpu.core.types import NodeInfo
from kubegpu_tpu.topology.mesh import ICIMesh

# LINK_DIRS bit positions for the negative direction of each axis
# (mesh.LINK_DIRS order: +x, -x, +y, -y, +z, -z).
_NEG_BITS = (1, 3, 5)


class ChipEntry:
    __slots__ = ("coords", "prefix", "node_name", "free", "links",
                 "hbm_free", "hbm_total")

    def __init__(self, coords: Tuple[int, ...], prefix: str,
                 node_name: str, free: bool, links: int, hbm_free: int,
                 hbm_total: int = 0) -> None:
        self.coords = coords
        self.prefix = prefix        # resource path prefix (.../tpu/<id>)
        self.node_name = node_name
        self.free = free
        self.links = links          # enumLinks bitmask (0 when absent)
        self.hbm_free = hbm_free    # allocatable - used HBM bytes
        self.hbm_total = hbm_total  # allocatable HBM (what eviction frees)


def collect_chips(node_infos: Dict[str, NodeInfo]) -> List[ChipEntry]:
    """All advertised chips across ``{node_name: NodeInfo}`` with
    coordinates, freeness, link masks, and free HBM."""
    chips = []
    for node_name, node_ex in node_infos.items():
        for res in node_ex.allocatable:
            chip_id = grammar.chip_id_from_path(res)
            if chip_id is None:
                continue
            coords = grammar.coords_from_chip_id(chip_id)
            if coords is None or len(coords) != 3:
                continue
            prefix = res[: -len(f"/{grammar.CHIPS_SUFFIX}")]
            links = node_ex.allocatable.get(
                f"{prefix}/{grammar.LINKS_SUFFIX}", 0)
            hbm_path = f"{prefix}/{grammar.HBM_SUFFIX}"
            hbm_total = node_ex.allocatable.get(hbm_path, 0)
            hbm_free = hbm_total - node_ex.used.get(hbm_path, 0)
            chips.append(ChipEntry(
                coords=coords, prefix=prefix, node_name=node_name,
                free=node_ex.used.get(res, 0) == 0, links=int(links),
                hbm_free=hbm_free, hbm_total=hbm_total))
    return chips


def mesh_from_chips(
        chips: List[ChipEntry]) -> Tuple[ICIMesh, Tuple[int, ...]]:
    """(ICIMesh, origin) spanning all advertised chips.

    Extent comes from the bounding box of *all* chips (not just free ones);
    per-axis wrap is detected from the link masks: a chip at the axis
    minimum advertising the negative-direction link implies a torus axis.
    """
    if not chips:
        raise ValueError("no chips")
    origin = tuple(min(c.coords[i] for c in chips) for i in range(3))
    extent = tuple(
        max(c.coords[i] for c in chips) - origin[i] + 1 for i in range(3))
    wrap = [False, False, False]
    for axis in range(3):
        if extent[axis] <= 1:
            continue
        for chip in chips:
            if chip.coords[axis] == origin[axis] and \
                    chip.links & (1 << _NEG_BITS[axis]):
                wrap[axis] = True
                break
    return ICIMesh(extent, tuple(wrap)), origin

"""Canonical inventory-shape trees.

A node's advertised group hierarchy canonicalizes to a sorted tree whose
shape is independent of group labels, so identical topologies dedup across
nodes and "which node shape fits this request best" is a tree lookup.
Reference: `device-scheduler/types/typeutils.go` (sorted tree) and
`plugins/gpuschedulerplugin/gpu.go:68-129` (building/scoring from the
resource list).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from kubegpu_tpu.utils import sorted_keys


@dataclass
class SortedTreeNode:
    """Tree node; children kept in descending (val, score) order.

    Reference: `device-scheduler/types/types.go:38-42`.
    """

    val: int = 0
    score: float = 0.0
    children: list = field(default_factory=list)

    def add_child(self, child: "SortedTreeNode") -> "SortedTreeNode":
        """Insert keeping descending order (`typeutils.go:5-29`)."""
        at = len(self.children)
        for i, existing in enumerate(self.children):
            if existing.val < child.val or (
                existing.val == child.val and existing.score < child.score
            ):
                at = i
                break
        self.children.insert(at, child)
        return child

    def add_value(self, val: int, score: float = 0.0) -> "SortedTreeNode":
        return self.add_child(SortedTreeNode(val=val, score=score))


def compare_trees(a: SortedTreeNode | None, b: SortedTreeNode | None) -> bool:
    """Structural equality on (val, children) — scores excluded
    (`typeutils.go:53-70`)."""
    if a is None and b is None:
        return True
    if a is None or b is None:
        return False
    if a.val != b.val or len(a.children) != len(b.children):
        return False
    return all(compare_trees(x, y) for x, y in zip(a.children, b.children))


def _score_at_level(node: SortedTreeNode, level: int, num_children: int) -> float:
    score = (node.val * level / num_children) if num_children else 0.0
    for child in node.children:
        score += _score_at_level(child, level + 1, len(node.children))
    return score


def compute_tree_score(node: SortedTreeNode) -> float:
    """Depth-weighted capacity score: deeper, denser hierarchies score
    higher, so auto-topology prefers the best-connected shape
    (`gpu.go:119-129`)."""
    return _score_at_level(node, 0, len(node.children))


def tree_from_resources(
    resources: dict,
    partition_prefix: str = "tpugrp",
    suffix: str = "chips",
    levels: int = 1,
) -> SortedTreeNode:
    """Canonicalize a group-resource list into a shape tree.

    ``levels=1`` consumes ``tpugrp1`` then ``tpugrp0`` (two grouping levels
    above the leaf), matching the reference call
    ``addToNode(nil, res, "gpugrp", "cards", 1)`` (`gpu.go:136`).
    """
    return _add_level(None, resources, partition_prefix, suffix, levels)


def _add_level(node, resources, partition_prefix, suffix, level):
    pattern = re.compile(
        rf".*/{partition_prefix}{level}/(.*?)/.*/{suffix}$")
    by_group: dict = {}
    total = 0
    for res_key in sorted_keys(resources):
        m = pattern.match(res_key)
        if m:
            by_group.setdefault(m.group(1), {})[res_key] = resources[res_key]
            total += 1
    if node is None:
        node = SortedTreeNode(val=total)
    for group_key in sorted_keys(by_group):
        sub = by_group[group_key]
        child = SortedTreeNode(val=len(sub))
        if level > 0:
            _add_level(child, sub, partition_prefix, suffix, level - 1)
            child.score = compute_tree_score(child)
        node.add_child(child)
    return node

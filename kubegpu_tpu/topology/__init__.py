"""ICI topology: 3-D torus/mesh modeling and contiguous sub-mesh search.

The TPU analogue of the reference's NVLink link-level grouping
(`nvidia_gpu_manager.go:93-121`), generalized: chips carry mesh coordinates,
links are modeled explicitly, and the placement constraint is "k chips must
form an ICI-contiguous sub-mesh" — strictly harder than the reference's
name-prefix grouping (SURVEY.md §8).
"""

from kubegpu_tpu.topology.mesh import ICIMesh, find_contiguous_block  # noqa: F401
from kubegpu_tpu.topology.tree import SortedTreeNode  # noqa: F401

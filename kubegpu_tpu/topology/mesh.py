"""3-D ICI mesh/torus math.

Models a TPU slice as a 3-D grid of chips with ICI links along +-x/+-y/+-z
(wraparound per axis for full-size torus dims, as on v4/v5p pods). Provides:

- per-chip ICI link-direction bitmasks (advertised as ``enumLinks``),
- contiguous sub-mesh search: given the free chip set, find ``count`` chips
  forming an ICI-connected block, preferring compact axis-aligned shapes
  and placements that fragment the remaining free space least,
- fragmentation scoring for bin-packing decisions.

All iteration is in sorted coordinate order so placement is deterministic
(the framework-wide rule, `docs/kubegpu.md:24-31` in the reference).
"""

from __future__ import annotations

import itertools
from functools import lru_cache

Coord = tuple  # (x, y, z)

# Link direction order defines enumLinks bit positions: bit i set <=> link
# present toward LINK_DIRS[i].
LINK_DIRS = (
    (1, 0, 0), (-1, 0, 0),
    (0, 1, 0), (0, -1, 0),
    (0, 0, 1), (0, 0, -1),
)


class ICIMesh:
    """A slice-shaped chip grid with optional per-axis wraparound."""

    def __init__(self, dims: tuple, wrap: tuple | bool = False):
        self.dims = tuple(int(d) for d in dims)
        if isinstance(wrap, bool):
            wrap = (wrap,) * len(self.dims)
        self.wrap = tuple(bool(w) for w in wrap)
        if len(self.dims) != 3 or len(self.wrap) != 3:
            raise ValueError(f"ICIMesh is 3-D; got dims={dims}")
        self.chips = [
            (x, y, z)
            for x in range(self.dims[0])
            for y in range(self.dims[1])
            for z in range(self.dims[2])
        ]
        self._chipset = set(self.chips)

    def __contains__(self, coord: Coord) -> bool:
        return tuple(coord) in self._chipset

    def size(self) -> int:
        return len(self.chips)

    def neighbor(self, coord: Coord, direction: Coord) -> Coord | None:
        """The chip one hop away, honoring wraparound; None off-mesh."""
        out = []
        for c, d, dim, w in zip(coord, direction, self.dims, self.wrap):
            n = c + d
            if w:
                n %= dim
            elif not 0 <= n < dim:
                return None
            out.append(n)
        nxt = tuple(out)
        # a wrapped link back to itself (dim 1 or 2) is not a distinct link
        return nxt if nxt != tuple(coord) else None

    def neighbors(self, coord: Coord) -> list:
        out = []
        for d in LINK_DIRS:
            n = self.neighbor(coord, d)
            if n is not None:
                out.append(n)
        return out

    def link_mask(self, coord: Coord) -> int:
        """ICI link-direction bitmask for one chip (the ``enumLinks`` value)."""
        mask = 0
        for i, d in enumerate(LINK_DIRS):
            if self.neighbor(coord, d) is not None:
                mask |= 1 << i
        return mask

    def is_connected(self, coords) -> bool:
        """Are these chips one ICI-connected component of the mesh?"""
        coords = set(map(tuple, coords))
        if not coords:
            return True
        seen = set()
        stack = [min(coords)]
        while stack:
            c = stack.pop()
            if c in seen or c not in coords:
                continue
            seen.add(c)
            for n in self.neighbors(c):
                if n in coords and n not in seen:
                    stack.append(n)
        return seen == coords

    def free_components(self, free) -> list:
        """Connected components of the free set, largest first."""
        free = set(map(tuple, free))
        comps = []
        while free:
            comp = set()
            stack = [min(free)]
            while stack:
                c = stack.pop()
                if c not in free or c in comp:
                    continue
                comp.add(c)
                stack.extend(n for n in self.neighbors(c) if n in free)
            free -= comp
            comps.append(comp)
        comps.sort(key=lambda c: (-len(c), min(c)))
        return comps

    def fragmentation_score(self, free) -> float:
        """1.0 = all free chips form one block; lower = more fragmented."""
        free = set(map(tuple, free))
        if not free:
            return 1.0
        comps = self.free_components(free)
        return len(comps[0]) / len(free)


@lru_cache(maxsize=256)
def _block_shapes(count: int) -> tuple:
    """Axis-aligned box shapes of volume ``count``, most compact first.

    Compactness = minimal surface area, the proxy for intra-block ICI hop
    distance (a 2x2x2 cube beats an 8x1x1 line for all-reduce latency).
    """
    shapes = set()
    for a in range(1, count + 1):
        if count % a:
            continue
        rest = count // a
        for b in range(1, rest + 1):
            if rest % b:
                continue
            c = rest // b
            shapes.update(itertools.permutations((a, b, c)))
    return tuple(sorted(shapes, key=lambda s: (
        s[0] * s[1] + s[1] * s[2] + s[0] * s[2], s)))


def _block_coords(origin: Coord, shape: tuple, mesh: ICIMesh):
    """Coords of the axis-aligned block at origin; None if it leaves the mesh."""
    coords = []
    for dx in range(shape[0]):
        for dy in range(shape[1]):
            for dz in range(shape[2]):
                c = []
                for o, d, dim, w in zip(origin, (dx, dy, dz), mesh.dims, mesh.wrap):
                    n = o + d
                    if n >= dim:
                        if not w:
                            return None
                        n %= dim
                    c.append(n)
                coords.append(tuple(c))
    if len(set(coords)) != len(coords):  # wrapped onto itself
        return None
    return coords


def _exposure(block, free, mesh: ICIMesh) -> int:
    """Free chips adjacent to (but outside) the block — the fragmentation
    a placement causes. Lower is better: prefer corners and edges."""
    blockset = set(block)
    seen = set()
    for c in block:
        for n in mesh.neighbors(c):
            if n in free and n not in blockset:
                seen.add(n)
    return len(seen)


def find_contiguous_block(mesh: ICIMesh, free, count: int):
    """Find ``count`` free chips forming an ICI-contiguous block.

    Strategy: try axis-aligned box shapes most-compact-first; among all
    placements of the best feasible shape pick the one exposing the fewest
    free neighbors (least future fragmentation), ties broken by sorted
    origin. Falls back to greedy compact connected growth when no box fits
    (fragmented free space). Returns a sorted coord list, or None if no
    connected set of that size exists.

    Dispatches to the native core (`native/contig.cpp`, built via
    ``make -C native``) when available — semantically identical,
    differentially tested; this Python implementation is the reference.
    """
    free = set(map(tuple, free))
    if count <= 0:
        return []
    if count > len(free):
        return None

    from kubegpu_tpu import native

    if native.get_lib() is not None:
        return native.native_find_contiguous_block(
            mesh.dims, mesh.wrap, free, count)

    for shape in _block_shapes(count):
        if any(s > d for s, d in zip(shape, mesh.dims)):
            continue
        best = None
        for origin in sorted(free):
            block = _block_coords(origin, shape, mesh)
            if block is None or not free.issuperset(block):
                continue
            key = (_exposure(block, free, mesh), origin)
            if best is None or key < best[0]:
                best = (key, block)
        if best is not None:
            return sorted(best[1])

    # Fragmented: grow a connected set greedily, preferring chips with the
    # most already-selected neighbors (keeps the blob compact).
    for comp in mesh.free_components(free):
        if len(comp) < count:
            continue
        blob = _greedy_blob(mesh, comp, min(comp), count)
        if blob is not None:
            return blob
    return None


def _greedy_blob(mesh: ICIMesh, comp, seed, count: int):
    """Grow a compact connected blob of ``count`` chips from ``seed``
    within component ``comp``; sorted coord list or None."""
    selected = [seed]
    selset = {seed}
    while len(selected) < count:
        frontier = {}
        for c in selected:
            for n in mesh.neighbors(c):
                if n in comp and n not in selset:
                    frontier[n] = frontier.get(n, 0) + 1
        if not frontier:
            return None
        nxt = max(sorted(frontier), key=lambda c: frontier[c])
        selected.append(nxt)
        selset.add(nxt)
    return sorted(selected)


def candidate_blocks(mesh: ICIMesh, free, count: int, limit: int = 64):
    """Yield candidate contiguous blocks in preference order.

    The gang planner needs MORE than the single best block: its chosen
    block must also split host-aligned, and the globally-best block may
    not (VERDICT r1 weak #2) — so every ranked (shape, origin) placement
    is yielded best-first, then greedy blobs seeded from each component
    chip for fragmented free space. ``find_contiguous_block``'s Python
    path equals the first yield; the native core is bypassed here since
    it returns only one block."""
    free = set(map(tuple, free))
    if count <= 0 or count > len(free):
        return
    yielded = 0
    seen: set = set()
    for shape in _block_shapes(count):
        if any(s > d for s, d in zip(shape, mesh.dims)):
            continue
        ranked = []
        for origin in sorted(free):
            block = _block_coords(origin, shape, mesh)
            if block is None or not free.issuperset(block):
                continue
            ranked.append(((_exposure(block, free, mesh), origin), block))
        for _, block in sorted(ranked, key=lambda kv: kv[0]):
            key = frozenset(block)
            if key in seen:
                continue
            seen.add(key)
            yield sorted(block)
            yielded += 1
            if yielded >= limit:
                return
    for comp in mesh.free_components(free):
        if len(comp) < count:
            continue
        for seed in sorted(comp):
            blob = _greedy_blob(mesh, comp, seed, count)
            if blob is None:
                continue
            key = frozenset(blob)
            if key in seen:
                continue
            seen.add(key)
            yield blob
            yielded += 1
            if yielded >= limit:
                return

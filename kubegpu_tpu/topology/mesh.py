"""3-D ICI mesh/torus math.

Models a TPU slice as a 3-D grid of chips with ICI links along +-x/+-y/+-z
(wraparound per axis for full-size torus dims, as on v4/v5p pods). Provides:

- per-chip ICI link-direction bitmasks (advertised as ``enumLinks``),
- contiguous sub-mesh search: given the free chip set, find ``count`` chips
  forming an ICI-connected block, preferring compact axis-aligned shapes
  and placements that fragment the remaining free space least,
- fragmentation scoring for bin-packing decisions.

The box-placement search runs as **bitmask shift-and-AND convolution**:
every candidate (shape, origin) placement's cell set and its mesh-neighbor
set are precomputed ONCE per (mesh geometry, count) as 64-bit word rows,
so one call reduces to ``(block & free) == block`` feasibility plus a
popcount for the fragmentation tie-break — numpy-vectorized over all
placements of a shape instead of a Python loop re-deriving each block.
The pre-vectorization implementation is retained verbatim as
``_find_contiguous_block_reference`` / ``_candidate_blocks_reference``:
it is the differential-test oracle the masked path is proven against.

All iteration is in sorted coordinate order so placement is deterministic
(the framework-wide rule, `docs/kubegpu.md:24-31` in the reference).
"""

from __future__ import annotations

import itertools
from functools import lru_cache
from typing import Any, Iterable, Iterator, List

try:  # optional acceleration; every caller falls back to the reference path
    import numpy as _np
except ImportError:  # pragma: no cover - numpy ships in the image
    _np = None

# The convolution tables popcount with np.bitwise_count (numpy >= 2.0);
# older numpy still powers the scheduler columns, but the mesh search
# must fall back to the reference path rather than crash mid-allocate.
_HAS_BITWISE_COUNT = _np is not None and hasattr(_np, "bitwise_count")

Coord = tuple  # (x, y, z)

# Link direction order defines enumLinks bit positions: bit i set <=> link
# present toward LINK_DIRS[i].
LINK_DIRS = (
    (1, 0, 0), (-1, 0, 0),
    (0, 1, 0), (0, -1, 0),
    (0, 0, 1), (0, 0, -1),
)


class ICIMesh:
    """A slice-shaped chip grid with optional per-axis wraparound."""

    def __init__(self, dims: tuple, wrap: "tuple | bool" = False) -> None:
        self.dims = tuple(int(d) for d in dims)
        if isinstance(wrap, bool):
            wrap = (wrap,) * len(self.dims)
        self.wrap = tuple(bool(w) for w in wrap)
        if len(self.dims) != 3 or len(self.wrap) != 3:
            raise ValueError(f"ICIMesh is 3-D; got dims={dims}")
        self.chips = [
            (x, y, z)
            for x in range(self.dims[0])
            for y in range(self.dims[1])
            for z in range(self.dims[2])
        ]
        self._chipset = set(self.chips)

    def __contains__(self, coord: Coord) -> bool:
        return tuple(coord) in self._chipset

    def size(self) -> int:
        return len(self.chips)

    def neighbor(self, coord: Coord, direction: Coord) -> Coord | None:
        """The chip one hop away, honoring wraparound; None off-mesh."""
        out = []
        for c, d, dim, w in zip(coord, direction, self.dims, self.wrap):
            n = c + d
            if w:
                n %= dim
            elif not 0 <= n < dim:
                return None
            out.append(n)
        nxt = tuple(out)
        # a wrapped link back to itself (dim 1 or 2) is not a distinct link
        return nxt if nxt != tuple(coord) else None

    def neighbors(self, coord: Coord) -> list:
        out = []
        for d in LINK_DIRS:
            n = self.neighbor(coord, d)
            if n is not None:
                out.append(n)
        return out

    def link_mask(self, coord: Coord) -> int:
        """ICI link-direction bitmask for one chip (the ``enumLinks`` value)."""
        mask = 0
        for i, d in enumerate(LINK_DIRS):
            if self.neighbor(coord, d) is not None:
                mask |= 1 << i
        return mask

    def is_connected(self, coords: Iterable[Coord]) -> bool:
        """Are these chips one ICI-connected component of the mesh?"""
        coords = set(map(tuple, coords))
        if not coords:
            return True
        seen = set()
        stack = [min(coords)]
        while stack:
            c = stack.pop()
            if c in seen or c not in coords:
                continue
            seen.add(c)
            for n in self.neighbors(c):
                if n in coords and n not in seen:
                    stack.append(n)
        return seen == coords

    def block_respects_links(self, block: Iterable[Coord],
                             link_of) -> bool:
        """Is every internal adjacency of ``block`` backed by a live,
        advertised ICI link? ``link_of(coord)`` returns the chip's
        advertised ``enumLinks`` mask (dead links already cleared by the
        node manager), or None when link info is unavailable — unknown
        never rejects, so legacy advertisers keep placing. Each edge is
        checked from BOTH endpoints: a one-sided cut (only one chip has
        reported the fault so far) is enough to exclude the block."""
        cells = set(map(tuple, block))
        for cell in cells:
            mask = link_of(cell)
            if mask is None:
                continue
            for i, d in enumerate(LINK_DIRS):
                if self.neighbor(cell, d) in cells and not mask & (1 << i):
                    return False
        return True

    def free_components(self, free: Iterable[Coord]) -> list:
        """Connected components of the free set, largest first."""
        free = set(map(tuple, free))
        comps = []
        while free:
            comp = set()
            stack = [min(free)]
            while stack:
                c = stack.pop()
                if c not in free or c in comp:
                    continue
                comp.add(c)
                stack.extend(n for n in self.neighbors(c) if n in free)
            free -= comp
            comps.append(comp)
        comps.sort(key=lambda c: (-len(c), min(c)))
        return comps

    def fragmentation_score(self, free: Iterable[Coord]) -> float:
        """1.0 = all free chips form one block; lower = more fragmented."""
        free = set(map(tuple, free))
        if not free:
            return 1.0
        comps = self.free_components(free)
        return len(comps[0]) / len(free)


@lru_cache(maxsize=256)
def _block_shapes(count: int) -> tuple:
    """Axis-aligned box shapes of volume ``count``, most compact first.

    Compactness = minimal surface area, the proxy for intra-block ICI hop
    distance (a 2x2x2 cube beats an 8x1x1 line for all-reduce latency).
    """
    shapes = set()
    for a in range(1, count + 1):
        if count % a:
            continue
        rest = count // a
        for b in range(1, rest + 1):
            if rest % b:
                continue
            c = rest // b
            shapes.update(itertools.permutations((a, b, c)))
    return tuple(sorted(shapes, key=lambda s: (
        s[0] * s[1] + s[1] * s[2] + s[0] * s[2], s)))


def _block_coords(origin: Coord, shape: tuple,
                  mesh: ICIMesh) -> "list | None":
    """Coords of the axis-aligned block at origin; None if it leaves the mesh."""
    coords = []
    for dx in range(shape[0]):
        for dy in range(shape[1]):
            for dz in range(shape[2]):
                c = []
                for o, d, dim, w in zip(origin, (dx, dy, dz), mesh.dims, mesh.wrap):
                    n = o + d
                    if n >= dim:
                        if not w:
                            return None
                        n %= dim
                    c.append(n)
                coords.append(tuple(c))
    if len(set(coords)) != len(coords):  # wrapped onto itself
        return None
    return coords


def _exposure(block: Iterable[Coord], free: set,
              mesh: ICIMesh) -> int:
    """Free chips adjacent to (but outside) the block — the fragmentation
    a placement causes. Lower is better: prefer corners and edges."""
    blockset = set(block)
    seen = set()
    for c in block:
        for n in mesh.neighbors(c):
            if n in free and n not in blockset:
                seen.add(n)
    return len(seen)


# ---- bitmask convolution placement tables -----------------------------------

# Meshes above this cell count skip table precomputation (a 128x128x1
# global mesh would cost tens of MB of mask rows per shape) and use the
# reference enumeration instead — the masked path exists for the per-host
# and gang-scale meshes the hot paths actually search.
MAX_TABLE_CELLS = 4096


class _ShapePlacements:
    """All valid placements of ONE box shape on one mesh geometry, as
    word-matrix rows in ascending-origin order: ``blocks[p]`` is the
    placement's cell bitmask, ``neighbors[p]`` its outside-the-block mesh
    neighborhood (what the fragmentation tie-break popcounts against the
    free mask), ``coords[p]`` the sorted cell list to hand back."""

    __slots__ = ("shape", "blocks", "neighbors", "coords", "origins")

    def __init__(self, shape: tuple, blocks: Any, neighbors: Any,
                 coords: List[list], origins: List[Coord]) -> None:
        self.shape = shape
        self.blocks = blocks        # np.uint64 [P, W]
        self.neighbors = neighbors  # np.uint64 [P, W]
        self.coords = coords        # list[P] of sorted coord lists
        self.origins = origins      # list[P] of origin coords


class _MaskTable:
    """Per-(mesh geometry, count) convolution table: one
    ``_ShapePlacements`` per feasible box shape, in the same
    most-compact-first shape order the reference search walks."""

    __slots__ = ("dims", "wrap", "count", "words", "shapes", "_bit")

    def __init__(self, mesh: ICIMesh, count: int) -> None:
        self.dims = mesh.dims
        self.wrap = mesh.wrap
        self.count = count
        nx, ny, _nz = mesh.dims
        self._bit = lambda c: c[0] + nx * (c[1] + ny * c[2])
        nbits = mesh.size()
        self.words = (nbits + 63) // 64
        self.shapes = []
        for shape in _block_shapes(count):
            if any(s > d for s, d in zip(shape, mesh.dims)):
                continue
            placements = self._placements(mesh, shape)
            if placements is not None:
                self.shapes.append(placements)

    def _placements(self, mesh: ICIMesh,
                    shape: tuple) -> "_ShapePlacements | None":
        rows_b, rows_n, coords_out, origins = [], [], [], []
        for origin in mesh.chips:  # ascending coord order == sorted(free)
            block = _block_coords(origin, shape, mesh)
            if block is None:
                continue
            blockset = set(block)
            bmask = 0
            nmask = 0
            for c in block:
                bmask |= 1 << self._bit(c)
                for n in mesh.neighbors(c):
                    if n not in blockset:
                        nmask |= 1 << self._bit(n)
            rows_b.append(self._words(bmask))
            rows_n.append(self._words(nmask))
            coords_out.append(sorted(block))
            origins.append(origin)
        if not rows_b:
            return None
        return _ShapePlacements(
            shape, _np.array(rows_b, dtype=_np.uint64),
            _np.array(rows_n, dtype=_np.uint64), coords_out, origins)

    def _words(self, mask: int) -> list:
        return [(mask >> (64 * w)) & 0xFFFFFFFFFFFFFFFF
                for w in range(self.words)]

    def free_words(self, free: Iterable[Coord]) -> "_np.ndarray":
        mask = 0
        bit = self._bit
        for c in free:
            mask |= 1 << bit(c)
        return _np.array(self._words(mask), dtype=_np.uint64)

    # twin-of: kubegpu_tpu.topology.mesh._find_contiguous_block_reference
    def best_block(self, free_row: "_np.ndarray") -> "list | None":
        """Most-compact-shape, least-exposure, smallest-origin placement
        fully inside the free mask — exactly the reference search's
        ``min((exposure, origin))`` over its box phase — or None."""
        for sp in self.shapes:
            contained = _np.bitwise_and(sp.blocks, free_row)
            feasible = _np.all(contained == sp.blocks, axis=1)
            if not feasible.any():
                continue
            idx = _np.flatnonzero(feasible)
            exposure = _np.bitwise_count(
                _np.bitwise_and(sp.neighbors[idx], free_row)).sum(axis=1)
            # stable first-minimum == smallest origin among ties (rows
            # are in ascending-origin order)
            return sp.coords[idx[int(_np.argmin(exposure))]]
        return None

    # twin-of: kubegpu_tpu.topology.mesh._candidate_blocks_reference
    def ranked_blocks(self,
                      free_row: "_np.ndarray") -> Iterator[list]:
        """Every feasible box placement, best-first ((exposure, origin)
        within each shape, shapes most-compact-first) — the masked twin
        of the reference's ranked ``candidate_blocks`` box phase."""
        for sp in self.shapes:
            contained = _np.bitwise_and(sp.blocks, free_row)
            feasible = _np.all(contained == sp.blocks, axis=1)
            if not feasible.any():
                continue
            idx = _np.flatnonzero(feasible)
            exposure = _np.bitwise_count(
                _np.bitwise_and(sp.neighbors[idx], free_row)).sum(axis=1)
            for j in _np.argsort(exposure, kind="stable"):
                yield sp.coords[idx[int(j)]]


_MASK_TABLES: dict = {}
_MAX_MASK_TABLES = 128


def _mask_table(mesh: ICIMesh, count: int) -> "_MaskTable | None":
    """The (geometry, count) convolution table, built once and cached —
    the enumeration cost the reference paid per call is paid per
    geometry here. None when numpy is absent or too old for
    ``bitwise_count``, or the mesh is too large to tabulate."""
    if not _HAS_BITWISE_COUNT or mesh.size() > MAX_TABLE_CELLS:
        return None
    key = (mesh.dims, mesh.wrap, count)
    table = _MASK_TABLES.get(key)
    if table is None:
        if len(_MASK_TABLES) >= _MAX_MASK_TABLES:
            _MASK_TABLES.pop(next(iter(_MASK_TABLES)))
        table = _MaskTable(mesh, count)
        _MASK_TABLES[key] = table
    return table


def find_contiguous_block(mesh: ICIMesh, free: Iterable[Coord],
                          count: int) -> "list | None":
    """Find ``count`` free chips forming an ICI-contiguous block.

    Strategy: try axis-aligned box shapes most-compact-first; among all
    placements of the best feasible shape pick the one exposing the fewest
    free neighbors (least future fragmentation), ties broken by sorted
    origin. Falls back to greedy compact connected growth when no box fits
    (fragmented free space). Returns a sorted coord list, or None if no
    connected set of that size exists.

    Dispatches to the native core (`native/contig.cpp`, built via
    ``make -C native``) when available, else to the bitmask convolution
    table — both semantically identical to (and differentially tested
    against) ``_find_contiguous_block_reference``.
    """
    free = set(map(tuple, free))
    if count <= 0:
        return []
    if count > len(free):
        return None

    from kubegpu_tpu import native

    if native.get_lib() is not None:
        return native.native_find_contiguous_block(
            mesh.dims, mesh.wrap, free, count)

    table = _mask_table(mesh, count)
    if table is not None:
        block = table.best_block(table.free_words(free))
        if block is not None:
            return block
    else:
        for shape in _block_shapes(count):
            if any(s > d for s, d in zip(shape, mesh.dims)):
                continue
            best = None
            for origin in sorted(free):
                block = _block_coords(origin, shape, mesh)
                if block is None or not free.issuperset(block):
                    continue
                key = (_exposure(block, free, mesh), origin)
                if best is None or key < best[0]:
                    best = (key, block)
            if best is not None:
                return sorted(best[1])

    # Fragmented: grow a connected set greedily, preferring chips with the
    # most already-selected neighbors (keeps the blob compact).
    for comp in mesh.free_components(free):
        if len(comp) < count:
            continue
        blob = _greedy_blob(mesh, comp, min(comp), count)
        if blob is not None:
            return blob
    return None


def _find_contiguous_block_reference(mesh: ICIMesh, free: Iterable[Coord],
                                     count: int) -> "list | None":
    """The pre-convolution pure-Python search, preserved verbatim as the
    differential-test oracle for both the native core and the masked
    path (`tests/test_vectorized.py` proves block-for-block equality)."""
    free = set(map(tuple, free))
    if count <= 0:
        return []
    if count > len(free):
        return None
    for shape in _block_shapes(count):
        if any(s > d for s, d in zip(shape, mesh.dims)):
            continue
        best = None
        for origin in sorted(free):
            block = _block_coords(origin, shape, mesh)
            if block is None or not free.issuperset(block):
                continue
            key = (_exposure(block, free, mesh), origin)
            if best is None or key < best[0]:
                best = (key, block)
        if best is not None:
            return sorted(best[1])
    for comp in mesh.free_components(free):
        if len(comp) < count:
            continue
        blob = _greedy_blob(mesh, comp, min(comp), count)
        if blob is not None:
            return blob
    return None


def _greedy_blob(mesh: ICIMesh, comp: set, seed: Coord,
                 count: int) -> "list | None":
    """Grow a compact connected blob of ``count`` chips from ``seed``
    within component ``comp``; sorted coord list or None."""
    selected = [seed]
    selset = {seed}
    while len(selected) < count:
        frontier = {}
        for c in selected:
            for n in mesh.neighbors(c):
                if n in comp and n not in selset:
                    frontier[n] = frontier.get(n, 0) + 1
        if not frontier:
            return None
        nxt = max(sorted(frontier), key=lambda c: frontier[c])
        selected.append(nxt)
        selset.add(nxt)
    return sorted(selected)


def candidate_blocks(mesh: ICIMesh, free: Iterable[Coord], count: int,
                     limit: int = 64) -> Iterator[list]:
    """Yield candidate contiguous blocks in preference order.

    The gang planner needs MORE than the single best block: its chosen
    block must also split host-aligned, and the globally-best block may
    not (VERDICT r1 weak #2) — so every ranked (shape, origin) placement
    is yielded best-first, then greedy blobs seeded from each component
    chip for fragmented free space. The box phase runs off the bitmask
    convolution table when available; the native core is bypassed here
    since it returns only one block."""
    free = set(map(tuple, free))
    if count <= 0 or count > len(free):
        return
    yielded = 0
    seen: set = set()
    table = _mask_table(mesh, count)
    if table is not None:
        for block in table.ranked_blocks(table.free_words(free)):
            key = frozenset(block)
            if key in seen:
                continue
            seen.add(key)
            yield block
            yielded += 1
            if yielded >= limit:
                return
    else:
        for shape in _block_shapes(count):
            if any(s > d for s, d in zip(shape, mesh.dims)):
                continue
            ranked = []
            for origin in sorted(free):
                block = _block_coords(origin, shape, mesh)
                if block is None or not free.issuperset(block):
                    continue
                ranked.append(((_exposure(block, free, mesh), origin), block))
            for _, block in sorted(ranked, key=lambda kv: kv[0]):
                key = frozenset(block)
                if key in seen:
                    continue
                seen.add(key)
                yield sorted(block)
                yielded += 1
                if yielded >= limit:
                    return
    for comp in mesh.free_components(free):
        if len(comp) < count:
            continue
        for seed in sorted(comp):
            blob = _greedy_blob(mesh, comp, seed, count)
            if blob is None:
                continue
            key = frozenset(blob)
            if key in seen:
                continue
            seen.add(key)
            yield blob
            yielded += 1
            if yielded >= limit:
                return


def _candidate_blocks_reference(mesh: ICIMesh, free: Iterable[Coord],
                                count: int,
                                limit: int = 64) -> Iterator[list]:
    """Pre-convolution ``candidate_blocks`` box+blob enumeration,
    preserved as the masked path's differential-test oracle."""
    free = set(map(tuple, free))
    if count <= 0 or count > len(free):
        return
    yielded = 0
    seen: set = set()
    for shape in _block_shapes(count):
        if any(s > d for s, d in zip(shape, mesh.dims)):
            continue
        ranked = []
        for origin in sorted(free):
            block = _block_coords(origin, shape, mesh)
            if block is None or not free.issuperset(block):
                continue
            ranked.append(((_exposure(block, free, mesh), origin), block))
        for _, block in sorted(ranked, key=lambda kv: kv[0]):
            key = frozenset(block)
            if key in seen:
                continue
            seen.add(key)
            yield sorted(block)
            yielded += 1
            if yielded >= limit:
                return
    for comp in mesh.free_components(free):
        if len(comp) < count:
            continue
        for seed in sorted(comp):
            blob = _greedy_blob(mesh, comp, seed, count)
            if blob is None:
                continue
            key = frozenset(blob)
            if key in seen:
                continue
            seen.add(key)
            yield blob
            yielded += 1
            if yielded >= limit:
                return

"""LoRA: low-rank adapter fine-tuning over the flagship transformer.

Parameter-efficient adaptation (the public LoRA recipe): each targeted
weight ``W [in, out]`` gains adapters ``A [in, r]`` and ``B [r, out]``
with ``B`` zero-initialized, and the model runs with the MERGED weight
``W + (alpha / r) * A @ B``. Merging per step instead of computing the
``(x @ A) @ B`` side branch is mathematically identical and costs one
``[in, r] @ [r, out]`` matmul per adapter per step — about ``r / (2*B*T)``
of the weight's own per-step FLOPs, well under 0.1% at practical sizes —
while keeping the forward (and the flash-attention path, remat policies,
sequence parallelism) completely unchanged.

Only the adapters train: the train step differentiates with respect to
the adapter pytree alone, so optimizer state is O(adapter) not O(model) —
the memory saving the method exists for. The frozen base params ride
along as a non-donated argument.

TPU notes: adapters stay f32 like the base master weights; the merge
casts to the compute dtype inside the model exactly as base weights do.
Sharding: ``A`` is replicated, ``B`` follows the base weight's OUTPUT
sharding (column-parallel targets shard B's last dim over ``model``), so
the merged weight has the base weight's sharding and GSPMD inserts no
extra collectives. The reference has no training runtime at all
(SURVEY.md §0); this module is part of the workload layer the TPU build
ships beyond it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from kubegpu_tpu.workload import spmd
from kubegpu_tpu.workload.model import TransformerConfig, make_loss_fn

DEFAULT_TARGETS = ("wq", "wv")  # the classic LoRA attention targets


def init_lora(rng, params: dict, rank: int,
              targets: tuple = DEFAULT_TARGETS) -> dict:
    """Adapter pytree mirroring ``params["layers"]``: per layer, per
    target, ``{"a": [in, r] (scaled normal), "b": [r, out] (zeros)}`` —
    zero ``b`` makes the merged model EQUAL the base model at init."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    layers = []
    for i, layer in enumerate(params["layers"]):
        k = jax.random.fold_in(rng, i)
        adapters = {}
        for j, name in enumerate(targets):
            if name not in layer:
                raise KeyError(
                    f"LoRA target {name!r} not in layer {i} "
                    f"(have: {sorted(k for k in layer if k != 'moe')})")
            d_in, d_out = layer[name].shape
            adapters[name] = {
                "a": jax.random.normal(jax.random.fold_in(k, j),
                                       (d_in, rank), jnp.float32)
                * (d_in ** -0.5),
                "b": jnp.zeros((rank, d_out), jnp.float32),
            }
        layers.append(adapters)
    return {"layers": layers}


def lora_pspecs(cfg: TransformerConfig,
                targets: tuple = DEFAULT_TARGETS) -> dict:
    """PartitionSpecs for the adapter pytree: ``a`` replicated (rank is
    tiny), ``b`` inheriting the base weight's output-dim sharding so the
    merged ``W + A @ B`` has the base weight's sharding exactly and
    GSPMD inserts no extra collectives. Derivable from the config alone,
    so the train step can apply it at build time."""
    from jax.sharding import PartitionSpec as P

    base = spmd.param_pspecs(cfg)
    layers = []
    for i in range(cfg.n_layers):
        specs = {}
        for name in targets:
            out_axis = base["layers"][i][name][1]  # base: P(in, out)
            specs[name] = {"a": P(None, None), "b": P(None, out_axis)}
        layers.append(specs)
    return {"layers": layers}


def merge_lora(params: dict, lora: dict, scaling: float) -> dict:
    """``W + scaling * A @ B`` for every adapted weight; other leaves are
    passed through by reference (no copies)."""
    merged_layers = []
    for layer, adapters in zip(params["layers"], lora["layers"]):
        new = dict(layer)
        for name, ab in adapters.items():
            new[name] = layer[name] + scaling * (ab["a"] @ ab["b"])
        merged_layers.append(new)
    return {**params, "layers": merged_layers}


def make_lora_train_step(cfg: TransformerConfig, mesh, rank: int,
                         optimizer=None, alpha: float | None = None,
                         targets: tuple = DEFAULT_TARGETS):
    """Jitted ``step(lora, opt_state, params, tokens) -> (lora, opt_state,
    loss)``: gradients and optimizer state over the ADAPTERS only; the
    base ``params`` are frozen (and not donated)."""
    from kubegpu_tpu.workload.train import default_optimizer

    optimizer = optimizer or default_optimizer()
    scaling = (alpha if alpha is not None else float(rank)) / rank
    loss_fn = make_loss_fn(cfg, mesh)

    def step(lora, opt_state, params, tokens):
        def lora_loss(lora):
            return loss_fn(merge_lora(params, lora, scaling), tokens)

        loss, grads = jax.value_and_grad(lora_loss)(lora)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        return lora, opt_state, loss

    if mesh is None:
        # donate the carried adapters + optimizer state exactly as the
        # mesh path below; the frozen base params (arg 2) stay undonated
        # traced-shapes: lora/opt_state adapter pytrees fixed by
        # cfg+rank; params pytree fixed by cfg; tokens [B, S] int32
        return jax.jit(step, donate_argnums=(0, 1))
    from jax.sharding import NamedSharding, PartitionSpec

    def named(tree):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), tree,
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    p_shard = named(spmd.param_pspecs(cfg))
    # adapters carry their documented layout through the step (B's
    # output dim sharded like the base weight), so host-created adapter
    # arrays are placed on first use and the merged weight needs no
    # resharding
    l_shard = named(lora_pspecs(cfg, targets))
    batch_shard = NamedSharding(mesh, spmd.batch_pspec())
    # traced-shapes: lora/opt_state adapter pytrees fixed by cfg+rank;
    # params pytree fixed by cfg; tokens [B, S] int32
    return jax.jit(
        step,
        in_shardings=(l_shard, None, p_shard, batch_shard),
        out_shardings=(l_shard, None, None),
        donate_argnums=(0, 1),
    )


def count_params(tree) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(tree))

"""Flagship model: a decoder-only transformer, TPU-first.

Design choices map straight to the hardware (see the repo prompt and
`/opt/skills/guides/pallas_guide.md` mental model):

- bfloat16 activations, float32 params/optimizer — MXU-friendly matmuls,
  stable accumulation;
- RoPE with *global* positions computed under GSPMD, so sequence-parallel
  shards agree without communication;
- attention is fused causal attention on a single shard, or — over the
  ``seq`` mesh axis — either ring attention (`kubegpu_tpu.workload.ring`)
  or Ulysses all-to-all sequence parallelism
  (`kubegpu_tpu.workload.ulysses`), chosen by ``seq_impl``;
- SwiGLU FFN, RMSNorm (no mean subtraction — cheaper on VPU);
- static shapes everywhere; layers run under `lax.scan`-free Python loop
  (n_layers is small and static) so XLA sees straight-line fusible HLO.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from kubegpu_tpu.workload import spmd
from kubegpu_tpu.workload.ring import make_sharded_ring_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 384
    max_seq: int = 1024
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"
    # Attention implementation: "xla" = einsum+softmax (XLA fuses it),
    # "flash" = Pallas flash kernel (kernels.flash), "auto" = flash on TPU
    # backends when the sequence tiles cleanly, else xla.
    attn_impl: str = "auto"
    # Sequence-parallel strategy when the mesh's seq axis is >1:
    # "ring" = K/V ppermute ring (`ring.py`), "ulysses" = all-to-all
    # head/sequence reshard (`ulysses.py`). Both are exact.
    seq_impl: str = "ring"
    # Mixture-of-experts FFN: 0 = dense; >0 replaces the FFN with top-k
    # routed experts sharded over the model axis (expert parallelism).
    # moe_top_k=1 is Switch semantics, >1 Mixtral (renormalized combine).
    n_experts: int = 0
    moe_top_k: int = 1
    moe_aux_weight: float = 0.01
    # Rematerialisation (activation checkpointing) per transformer layer —
    # the TPU trade of FLOPs for HBM (scaling-book recipe; the reference
    # has no training runtime, SURVEY.md §0):
    #   "none" — save all activations (fastest per-step, most HBM);
    #   "dots" — jax.checkpoint with dots_with_no_batch_dims_saveable:
    #            keep matmul outputs, recompute elementwise/softmax;
    #   "full" — save only layer boundaries, recompute the whole layer
    #            in backward (~+1 fwd of FLOPs, minimal HBM).
    remat: str = "none"

    # Sliding-window attention: each position attends only the newest
    # ``attn_window`` positions (0 = full causal). Works on every
    # attention path — xla, the flash kernel (which skips fully-out-of-
    # window tiles), and the cross-shard seq strategies (the ring masks
    # each rotating block at global positions; Ulysses attends the full
    # sequence locally) — long-range information still flows across
    # layers, Mistral-style.
    attn_window: int = 0
    # Grouped-query attention: 0 = MHA (kv heads == query heads); a
    # divisor of n_heads shares each K/V head across n_heads/n_kv_heads
    # query heads — smaller KV projections and an n_heads/n_kv_heads
    # smaller decode cache (decode is HBM-bandwidth-bound on TPU, so the
    # cache size is the knob that matters).
    n_kv_heads: int = 0

    def __post_init__(self):
        if self.attn_window < 0:
            raise ValueError(
                f"attn_window must be >= 0, got {self.attn_window}")
        if self.n_experts > 0 and not 1 <= self.moe_top_k <= self.n_experts:
            raise ValueError(
                f"moe_top_k {self.moe_top_k} must be in "
                f"[1, n_experts={self.n_experts}]")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads or self.n_heads
        if self.n_heads % kv:
            raise ValueError(
                f"n_kv_heads {kv} must divide n_heads {self.n_heads}")
        return kv

    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def init_params(rng, cfg: TransformerConfig) -> dict:
    """Parameter pytree; structure mirrors `spmd.param_pspecs` exactly."""
    k_embed, k_unembed, k_layers = jax.random.split(rng, 3)
    d, h, f = cfg.d_model, cfg.n_heads * cfg.head_dim, cfg.d_ff
    kv = cfg.kv_heads * cfg.head_dim  # GQA: K/V project to fewer heads

    def dense(key, shape):
        scale = (shape[0]) ** -0.5
        return jax.random.normal(key, shape, jnp.float32) * scale

    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.fold_in(k_layers, i)
        kq, kk, kv_key, ko, ku, kg, kd = jax.random.split(k, 7)
        layer = {
            "ln1": jnp.ones((d,), jnp.float32),
            "wq": dense(kq, (d, h)),
            "wk": dense(kk, (d, kv)),
            "wv": dense(kv_key, (d, kv)),
            "wo": dense(ko, (h, d)),
            "ln2": jnp.ones((d,), jnp.float32),
        }
        if cfg.n_experts > 0:
            from kubegpu_tpu.workload.moe import init_moe_params

            layer["moe"] = init_moe_params(ku, d, f, cfg.n_experts)
        else:
            layer.update({
                "w_up": dense(ku, (d, f)),
                "w_gate": dense(kg, (d, f)),
                "w_down": dense(kd, (f, d)),
            })
        layers.append(layer)
    return {
        "embed": jax.random.normal(k_embed, (cfg.vocab, d), jnp.float32) * 0.02,
        "unembed": dense(k_unembed, (d, cfg.vocab)),
        "final_norm": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }


def _rmsnorm(x, gain):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * gain.astype(x.dtype)


def _rope(x, positions, theta: float):
    """Rotary embedding; ``positions`` are global sequence positions."""
    _, _, _, d = x.shape
    half = d // 2
    freqs = jnp.exp(-jnp.log(theta) * jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B,T,half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _causal_attention(q, k, v, scale: float, window: int = 0):
    """Single-shard fused causal attention ([B,T,H,D] layout);
    ``window`` > 0 = sliding-window (newest ``window`` keys only).

    Operands stay in the compute dtype (bf16) with f32 ACCUMULATION
    (``preferred_element_type``) — the MXU's native mode. Casting inputs
    to f32 before the einsum would run the matmuls at 1/4 the bf16 rate
    for no extra accumulator precision."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    if window:
        pos = jnp.arange(t)
        mask &= pos[None, :] > pos[:, None] - window
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _expand_kv(cfg: TransformerConfig, k, v):
    """GQA: broadcast each K/V head across its n_heads/kv_heads query
    group so every attention implementation (xla einsum, flash kernel,
    ring, Ulysses) sees plain MHA tensors. The PARAMS and the decode
    cache stay at kv_heads — the savings GQA exists for — only this
    transient is full-width."""
    if cfg.kv_heads == cfg.n_heads:
        return k, v
    rep = cfg.n_heads // cfg.kv_heads
    return (jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2))


def _resolve_attn_impl(cfg: TransformerConfig, seq_len: int) -> str:
    """Pick the attention implementation for a given local sequence length.

    "auto" uses the Pallas flash kernel only on a TPU default backend and
    only when the sequence tiles onto the MXU (multiple of 128); the CPU
    interpret path exists for tests but is not worth it for real runs."""
    if cfg.attn_impl != "auto":
        return cfg.attn_impl
    on_tpu = jax.default_backend() == "tpu"
    return "flash" if on_tpu and seq_len % 128 == 0 else "xla"


def make_forward_with_aux(cfg: TransformerConfig, mesh=None):
    """Build ``forward(params, tokens) -> (logits, aux_loss)``.

    With a mesh whose ``seq`` axis is >1, attention runs as ring attention
    over that axis; otherwise fused single-shard attention. Everything else
    is GSPMD-sharded via constraints + param shardings. ``aux_loss`` is the
    MoE load-balancing term (0.0 for dense configs).
    """
    use_ring = mesh is not None and mesh.shape.get(spmd.AXIS_SEQ, 1) > 1
    seq_shards = mesh.shape.get(spmd.AXIS_SEQ, 1) if mesh is not None else 1
    scale = cfg.head_dim ** -0.5

    def attention_fn(t: int):
        """Resolve the attend callable once the sequence length is known."""
        # Ulysses attends the FULL sequence locally after the all-to-all,
        # so the flash-tiling decision sees t, not t // seq_shards.
        local_t = t if cfg.seq_impl == "ulysses" else t // seq_shards
        impl = _resolve_attn_impl(cfg, local_t)
        interpret = impl == "flash" and jax.default_backend() == "cpu"
        if use_ring and cfg.seq_impl == "ulysses":
            from kubegpu_tpu.workload.ulysses import (
                make_sharded_ulysses_attention)

            return make_sharded_ulysses_attention(
                mesh, spmd.AXIS_DATA, spmd.AXIS_SEQ, spmd.AXIS_MODEL, scale,
                use_flash=impl == "flash", interpret=interpret,
                window=cfg.attn_window)
        if use_ring:
            return make_sharded_ring_attention(
                mesh, spmd.AXIS_DATA, spmd.AXIS_SEQ, spmd.AXIS_MODEL, scale,
                use_flash=impl == "flash", interpret=interpret,
                window=cfg.attn_window)
        if impl == "flash":
            from kubegpu_tpu.workload.kernels.flash import flash_attention

            return lambda q, k, v: flash_attention(
                q, k, v, scale, interpret=interpret,
                window=cfg.attn_window)
        return lambda q, k, v: _causal_attention(q, k, v, scale,
                                                 window=cfg.attn_window)

    def constrain(x, *spec):
        if mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))

    def make_block(attend):
        """One transformer layer as ``block(layer, x, positions) ->
        (x, aux)`` so `jax.checkpoint` can wrap exactly one layer's
        activations (the remat unit)."""

        def block(layer, x, positions):
            dt = cfg.compute_dtype()
            b, t = x.shape[:2]
            h = _rmsnorm(x, layer["ln1"])
            q = (h @ layer["wq"].astype(dt)).reshape(b, t, cfg.n_heads, cfg.head_dim)
            k = (h @ layer["wk"].astype(dt)).reshape(b, t, cfg.kv_heads, cfg.head_dim)
            v = (h @ layer["wv"].astype(dt)).reshape(b, t, cfg.kv_heads, cfg.head_dim)
            q = _rope(q, positions, cfg.rope_theta)
            k = _rope(k, positions, cfg.rope_theta)
            k, v = _expand_kv(cfg, k, v)
            attn = checkpoint_name(attend(q, k, v), "attn_out")
            x = x + attn.reshape(b, t, -1) @ layer["wo"].astype(dt)
            x = constrain(x, spmd.AXIS_DATA, spmd.AXIS_SEQ, None)

            h = _rmsnorm(x, layer["ln2"])
            if "moe" in layer:
                from kubegpu_tpu.workload.moe import moe_ffn

                ffn_out, aux = moe_ffn(layer["moe"], h, dt,
                                       top_k=cfg.moe_top_k)
                x = x + ffn_out
            else:
                up = h @ layer["w_up"].astype(dt)
                gate = jax.nn.silu(h @ layer["w_gate"].astype(dt))
                x = x + (up * gate) @ layer["w_down"].astype(dt)
                aux = jnp.zeros((), jnp.float32)
            x = constrain(x, spmd.AXIS_DATA, spmd.AXIS_SEQ, None)
            return x, aux

        if cfg.remat == "full":
            return jax.checkpoint(block)
        if cfg.remat == "dots":
            # matmul outputs PLUS the named attention residuals: the
            # attention einsums have batch dims (so the dots policy alone
            # recomputes them), and the flash kernel's custom VJP would
            # re-run its whole forward to regenerate (o, lse) — saving
            # "attn_out"/"attn_lse" (~1 activation per layer) avoids both.
            return jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names(
                        "attn_out", "attn_lse")))
        if cfg.remat != "none":
            raise ValueError(f"unknown remat mode {cfg.remat!r}")
        return block

    def forward(params, tokens):
        dt = cfg.compute_dtype()
        b, t = tokens.shape
        x = params["embed"].astype(dt)[tokens]
        x = constrain(x, spmd.AXIS_DATA, spmd.AXIS_SEQ, None)
        positions = jnp.broadcast_to(jnp.arange(t), (b, t))
        aux_total = jnp.zeros((), jnp.float32)
        block = make_block(attention_fn(t))

        for layer in params["layers"]:
            x, aux = block(layer, x, positions)
            aux_total = aux_total + aux

        x = _rmsnorm(x, params["final_norm"])
        logits = x @ params["unembed"].astype(dt)
        return logits.astype(jnp.float32), aux_total

    return forward


def make_forward(cfg: TransformerConfig, mesh=None):
    """``forward(params, tokens) -> logits`` (aux loss discarded)."""
    fwd = make_forward_with_aux(cfg, mesh)

    def forward(params, tokens):
        logits, _ = fwd(params, tokens)
        return logits

    return forward


def make_loss_fn(cfg: TransformerConfig, mesh=None):
    """Next-token cross entropy over ``tokens [B, T+1]`` (+ MoE aux)."""
    fwd = make_forward_with_aux(cfg, mesh)

    def loss_fn(params, tokens):
        logits, aux = fwd(params, tokens[:, :-1])
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return nll.mean() + cfg.moe_aux_weight * aux

    return loss_fn

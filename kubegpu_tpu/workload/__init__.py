"""The workload layer: the SPMD JAX job this framework schedules.

The scheduler's product is a set of ICI-contiguous chips handed to a
container as ``TPU_VISIBLE_CHIPS``; this package is the other half of that
contract — it turns an allocation into a `jax.sharding.Mesh` and runs a
sharded transformer training step on it (data/tensor/sequence parallelism,
ring attention for long context). It is also the flagship model behind
``__graft_entry__.py`` and the compute side of ``bench.py``.
"""

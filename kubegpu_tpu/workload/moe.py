"""Mixture-of-experts FFN with expert parallelism.

Experts are sharded across the ``model`` mesh axis (expert parallelism
rides the same tensor-parallel devices): expert weight tensors carry a
leading expert dimension partitioned over ``model``, and GSPMD inserts the
dispatch/combine collectives implied by the routing einsums.

Routing is top-k over a jitter-free softmax gate: Switch-style top-1
(raw gate weight) by default, Mixtral-style top-k with renormalized
combine weights for ``top_k > 1``. Compute is dense-over-experts (every
expert runs on every token, selection by the combine weights). That
trades FLOPs for simplicity and static shapes — the capacity-factor
dispatch kernel is a later optimization, not a semantic change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int) -> dict:
    k_router, k_up, k_gate, k_down = jax.random.split(rng, 4)
    scale_in = d_model**-0.5
    scale_out = d_ff**-0.5
    return {
        "router": jax.random.normal(k_router, (d_model, n_experts),
                                    jnp.float32) * scale_in,
        "w_up": jax.random.normal(k_up, (n_experts, d_model, d_ff),
                                  jnp.float32) * scale_in,
        "w_gate": jax.random.normal(k_gate, (n_experts, d_model, d_ff),
                                    jnp.float32) * scale_in,
        "w_down": jax.random.normal(k_down, (n_experts, d_ff, d_model),
                                    jnp.float32) * scale_out,
    }


def moe_pspecs(model_axis: str) -> dict:
    from jax.sharding import PartitionSpec as P

    return {
        "router": P(None, None),
        "w_up": P(model_axis, None, None),    # experts sharded: EP
        "w_gate": P(model_axis, None, None),
        "w_down": P(model_axis, None, None),
    }


def moe_ffn(params: dict, x, compute_dtype, top_k: int = 1) -> tuple:
    """Top-k routed SwiGLU experts. Returns (output, aux_loss).

    ``top_k == 1`` keeps Switch semantics exactly (output scaled by the
    winner's RAW gate probability); ``top_k > 1`` uses Mixtral semantics
    (combine weights renormalized over the selected experts).
    ``aux_loss`` is the standard load-balancing loss (mean gate fraction
    x mean route fraction x n_experts), encouraging uniform expert load.
    """
    gate_logits = x.astype(jnp.float32) @ params["router"]
    gates = jax.nn.softmax(gate_logits, axis=-1)           # [B,T,E]
    n_experts = gates.shape[-1]
    if not 1 <= top_k <= n_experts:
        raise ValueError(
            f"top_k {top_k} must be in [1, n_experts={n_experts}]")
    vals, idx = jax.lax.top_k(gates, top_k)                # [B,T,K]
    if top_k > 1:
        vals = vals / jnp.sum(vals, axis=-1, keepdims=True)
    hot = jax.nn.one_hot(idx, n_experts, dtype=gates.dtype)  # [B,T,K,E]
    combine = jnp.sum(hot * vals[..., None], axis=2)       # [B,T,E]

    # dense-over-experts compute; combine by the routing weights
    up = jnp.einsum("btd,edf->btef", x, params["w_up"].astype(compute_dtype))
    gate = jax.nn.silu(
        jnp.einsum("btd,edf->btef", x, params["w_gate"].astype(compute_dtype)))
    expert_out = jnp.einsum("btef,efd->bted", up * gate,
                            params["w_down"].astype(compute_dtype))
    out = jnp.einsum("bted,bte->btd", expert_out,
                     combine.astype(compute_dtype))

    # load-balancing aux loss (Switch Transformer eq. 4, normalized so
    # the ideal-uniform value stays 1.0 for any k)
    dispatch = jnp.sum(hot, axis=2)                        # [B,T,E] 0/1
    route_frac = dispatch.mean(axis=(0, 1)) / top_k        # [E]
    gate_frac = gates.mean(axis=(0, 1))                    # [E]
    aux = n_experts * jnp.sum(route_frac * gate_frac)
    return out, aux

"""Mixture-of-experts FFN with expert parallelism.

Experts are sharded across the ``model`` mesh axis (expert parallelism
rides the same tensor-parallel devices): expert weight tensors carry a
leading expert dimension partitioned over ``model``, and GSPMD inserts the
dispatch/combine collectives implied by the routing einsums.

Routing is switch-style top-1 with a jitter-free softmax gate; compute is
dense-over-experts (every expert runs on every token, selection by one-hot
combine). That trades FLOPs for simplicity and static shapes — the
capacity-factor dispatch kernel is a later optimization, not a semantic
change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int) -> dict:
    k_router, k_up, k_gate, k_down = jax.random.split(rng, 4)
    scale_in = d_model**-0.5
    scale_out = d_ff**-0.5
    return {
        "router": jax.random.normal(k_router, (d_model, n_experts),
                                    jnp.float32) * scale_in,
        "w_up": jax.random.normal(k_up, (n_experts, d_model, d_ff),
                                  jnp.float32) * scale_in,
        "w_gate": jax.random.normal(k_gate, (n_experts, d_model, d_ff),
                                    jnp.float32) * scale_in,
        "w_down": jax.random.normal(k_down, (n_experts, d_ff, d_model),
                                    jnp.float32) * scale_out,
    }


def moe_pspecs(model_axis: str) -> dict:
    from jax.sharding import PartitionSpec as P

    return {
        "router": P(None, None),
        "w_up": P(model_axis, None, None),    # experts sharded: EP
        "w_gate": P(model_axis, None, None),
        "w_down": P(model_axis, None, None),
    }


def moe_ffn(params: dict, x, compute_dtype) -> tuple:
    """Top-1 routed SwiGLU experts. Returns (output, aux_loss).

    ``aux_loss`` is the standard load-balancing loss (mean gate fraction x
    mean route fraction x n_experts), encouraging uniform expert load.
    """
    gate_logits = x.astype(jnp.float32) @ params["router"]
    gates = jax.nn.softmax(gate_logits, axis=-1)          # [B,T,E]
    top1 = jnp.argmax(gates, axis=-1)                      # [B,T]
    n_experts = gates.shape[-1]
    one_hot = jax.nn.one_hot(top1, n_experts, dtype=gates.dtype)
    top_gate = jnp.sum(gates * one_hot, axis=-1)           # [B,T]

    # dense-over-experts compute; combine by the routing one-hot
    up = jnp.einsum("btd,edf->btef", x, params["w_up"].astype(compute_dtype))
    gate = jax.nn.silu(
        jnp.einsum("btd,edf->btef", x, params["w_gate"].astype(compute_dtype)))
    expert_out = jnp.einsum("btef,efd->bted", up * gate,
                            params["w_down"].astype(compute_dtype))
    out = jnp.einsum("bted,bte->btd", expert_out,
                     one_hot.astype(compute_dtype))
    out = out * top_gate[..., None].astype(compute_dtype)

    # load-balancing aux loss (Switch Transformer eq. 4)
    route_frac = one_hot.mean(axis=(0, 1))                 # [E]
    gate_frac = gates.mean(axis=(0, 1))                    # [E]
    aux = n_experts * jnp.sum(route_frac * gate_frac)
    return out, aux

"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Layers are split into S stages; each stage's parameters live on one rank of
the ``stage`` mesh axis (params carry a leading stage dimension partitioned
over it). Activations flow stage-to-stage via neighbor `lax.ppermute` — the
collective-pipelining recipe: every rank runs the same program, stage 0
injects a fresh microbatch per step, stage S-1 emits one, and the classic
(S-1)-step bubble fills/drains at the ends.

Composes with data parallelism (``data`` axis stays GSPMD-sharded outside);
sequence parallelism inside a stage is future work — nesting manual
collectives needs partial-manual shard_map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

AXIS_STAGE = "stage"


def stack_stage_params(per_stage_params: list):
    """Stack a list of per-stage param pytrees along a leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def make_pipelined_apply(stage_fn, mesh, n_microbatches: int,
                         stage_axis: str = AXIS_STAGE):
    """Build ``apply(stacked_params, x) -> y`` running the stage pipeline.

    - ``stage_fn(stage_params, x_mb) -> y_mb`` must be shape-preserving
      (transformer blocks: [mb, T, D] -> [mb, T, D]).
    - ``stacked_params``: leading-stage-dim pytree, sharded P(stage, ...).
    - ``x``: [n_microbatches, mb, ...] microbatched input, replicated over
      the stage axis; output has the same shape, replicated.
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[stage_axis]

    def per_rank(stacked_local, x):
        # stacked_local leaves have leading dim 1 (this rank's stage slice)
        params_local = jax.tree.map(lambda a: a[0], stacked_local)
        stage = lax.axis_index(stage_axis)
        total_steps = n_microbatches + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        mb_shape = x.shape[1:]
        out_buf = jnp.zeros((n_microbatches,) + mb_shape, x.dtype)
        carry = jnp.zeros(mb_shape, x.dtype)

        def step(state, t):
            carry, out_buf = state
            # stage 0 injects microbatch t (clamped; masked past the end)
            inject = jnp.logical_and(stage == 0, t < n_microbatches)
            idx = jnp.minimum(t, n_microbatches - 1)
            fresh = lax.dynamic_index_in_dim(x, idx, axis=0, keepdims=False)
            inp = jnp.where(inject, fresh, carry)

            out = stage_fn(params_local, inp)

            # the last stage emits microbatch t-(S-1)
            emit_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
            is_emit = jnp.logical_and(stage == n_stages - 1,
                                      t >= n_stages - 1)
            current = lax.dynamic_index_in_dim(out_buf, emit_idx, axis=0,
                                               keepdims=False)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(is_emit, out, current), emit_idx, axis=0)

            # hand activations to the next stage (stage S-1 sends nowhere;
            # stage 0 receives zeros)
            carry = out if n_stages == 1 else lax.ppermute(
                out, stage_axis, perm)
            return (carry, out_buf), None

        (carry, out_buf), _ = lax.scan(
            step, (carry, out_buf), jnp.arange(total_steps))
        # only the last stage holds real outputs; psum replicates them
        mask = (stage == n_stages - 1).astype(x.dtype)
        return lax.psum(out_buf * mask, stage_axis)

    # P(stage_axis) is a prefix spec: it applies to every param leaf's
    # leading stage dimension; inputs/outputs are stage-replicated.
    return jax.shard_map(
        per_rank, mesh=mesh,
        in_specs=(P(stage_axis), P()),
        out_specs=P(),
        check_vma=False)


def split_layers_into_stages(layers: list, n_stages: int) -> list:
    """Partition a layer list into n_stages contiguous groups (balanced)."""
    if len(layers) % n_stages != 0:
        raise ValueError(f"{len(layers)} layers not divisible by "
                         f"{n_stages} stages")
    per = len(layers) // n_stages
    return [layers[i * per:(i + 1) * per] for i in range(n_stages)]

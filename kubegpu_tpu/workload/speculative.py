"""Speculative decoding: draft-proposes, target-verifies, greedy-exact.

The standard two-model speedup for autoregressive decoding: a small
draft model proposes ``k`` tokens with cheap sequential steps, the large
target model scores all of them in ONE forward pass (sequential decode
becomes a parallel verify), and the longest agreeing prefix is accepted
plus the target's own next token. With greedy selection the output is
EXACTLY the target model's greedy sequence — acceptance only changes
how many target forwards it takes, never the tokens (asserted by
tests/test_speculative.py).

TPU-static design: every device program has fixed shapes — the draft
proposal is a ``k``-step `lax.scan`, the verify is one ``k+1``-token
chunked forward (`make_forward_step`), and the data-dependent acceptance
length only travels to the host as a scalar. Rejected positions leave
stale K/V in both caches; that is safe for the same reason the serve
loop's padded prefill is: position ``p`` is rewritten exactly when the
real token at ``p`` is processed, and queries only attend positions
that have been rewritten.

Numerics: exactness vs `make_generate` holds bit-for-bit in float32
(asserted by tests). On TPU in bfloat16 the (k+1)-chunk verify rounds
differently than the reference's one-token steps (MXU results are
shape-dependent), so near-tie argmaxes can flip — the same documented
class as the serve loop's padded prefill and immaterial for trained
models. Acceptance is unaffected: a self-draft run on a real v5e hit
12 target calls for 48 tokens (ideal 11).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubegpu_tpu.workload.decode import init_cache, make_forward_step
from kubegpu_tpu.workload.model import TransformerConfig


def make_speculative_generate(target_cfg: TransformerConfig,
                              draft_cfg: TransformerConfig,
                              k: int = 4, mesh=None,
                              max_seq: int | None = None):
    """Build ``generate(target_params, draft_params, prompt, n_new) ->
    (tokens [B=1 row list], target_calls)``.

    Greedy-only: greedy acceptance is exact, so sampling would need the
    rejection-resampling scheme — out of scope here. ``k`` is the draft
    lookahead per round. Both models must share the vocab.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    max_seq = max_seq or min(target_cfg.max_seq, draft_cfg.max_seq)
    t_step = make_forward_step(target_cfg, mesh)
    d_step = make_forward_step(draft_cfg, mesh)

    def prefill(params, step, cache, prompt):
        logits, cache = step(params, cache, prompt, 0)
        return cache, jnp.argmax(logits[:, -1, :], axis=-1)

    prefill_t = jax.jit(lambda p, c, x: prefill(p, t_step, c, x))
    prefill_d = jax.jit(lambda p, c, x: prefill(p, d_step, c, x))

    def draft_propose(params, cache, prev, token, pos):
        """k greedy draft proposals from ``token`` at ``pos``.

        The first step processes the 2-token chunk ``[prev, token]`` at
        ``pos-1``: after a fully-accepted round the draft never
        processed its own k-th proposal, leaving a K/V hole at exactly
        ``pos-1`` — re-processing ``prev`` there fills the hole (and is
        an idempotent rewrite when no hole exists). Without this, the
        round after a full accept proposes against a zeroed cache row
        and acceptance collapses."""
        chunk = jnp.stack([prev, token], axis=1)        # [1, 2]
        logits, cache = d_step(params, cache, chunk, pos - 1)
        first = jnp.argmax(logits[:, -1, :], axis=-1)

        def body(carry, _):
            cache, tok, p = carry
            logits, cache = d_step(params, cache, tok[:, None], p)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1)
            return (cache, nxt, p + 1), nxt

        (cache, _, _), toks = lax.scan(
            body, (cache, first, pos + 1), None, length=k - 1)
        drafts = jnp.concatenate([first, toks[:, 0]]) if k > 1 else first
        return cache, drafts  # [k]

    draft_propose = jax.jit(draft_propose)

    def verify(params, cache, chunk, pos):
        """One target forward over ``chunk [1, k+1]`` (last accepted token
        + k draft tokens) at ``pos``; returns the target's greedy token
        AFTER each chunk position ([k+1]) and the number of accepted
        draft tokens."""
        logits, cache = t_step(params, cache, chunk, pos)
        greedy = jnp.argmax(logits[0], axis=-1)           # [k+1]
        drafts = chunk[0, 1:]                             # [k]
        agree = drafts == greedy[:-1]
        n_acc = jnp.argmin(jnp.concatenate(
            [agree, jnp.array([False])]).astype(jnp.int32))
        return cache, greedy, n_acc

    verify = jax.jit(verify)

    def generate(target_params, draft_params, prompt, n_new: int):
        if n_new < 1:
            raise ValueError(f"n_new must be >= 1, got {n_new}")
        prompt = jnp.asarray(prompt, jnp.int32).reshape(1, -1)
        t0 = prompt.shape[1]
        if t0 + n_new + k + 1 > max_seq:
            raise ValueError(
                f"prompt ({t0}) + n_new ({n_new}) + lookahead ({k + 1}) "
                f"exceeds max_seq ({max_seq})")
        # Horizon-sized caches, exactly as decode.make_generate: the
        # full-cache attention read is the HBM traffic that bounds
        # decode, and positions past this call's reach contribute zero.
        # +k+1: verify may write up to k+1 positions past the last
        # emitted token before truncation.
        horizon = min(max_seq, -(-(t0 + n_new + k + 1) // 128) * 128)
        t_cache = init_cache(target_cfg, 1, horizon)
        d_cache = init_cache(draft_cfg, 1, horizon)
        t_cache, first = prefill_t(target_params, t_cache, prompt)
        d_cache, _ = prefill_d(draft_params, d_cache, prompt)

        out = [int(np.asarray(first)[0])]
        pos = t0            # both caches hold [0, t0); `first` unprocessed
        target_calls = 1
        last = first        # [1] last accepted-but-unprocessed token
        prev = prompt[:, -1]  # token at pos-1 (draft catch-up anchor)
        while len(out) < n_new:
            d_cache, drafts = draft_propose(draft_params, d_cache, prev,
                                            last, jnp.int32(pos))
            chunk = jnp.concatenate([last, drafts]).reshape(1, k + 1)
            t_cache, greedy, n_acc = verify(target_params, t_cache, chunk,
                                            jnp.int32(pos))
            target_calls += 1
            n_acc = int(n_acc)
            greedy = np.asarray(greedy)
            drafts_np = np.asarray(drafts)
            # accepted draft tokens, then the target's own next token
            # (the correction on mismatch; the bonus when all k agree)
            new = [int(x) for x in drafts_np[:n_acc]] + [int(greedy[n_acc])]
            out.extend(new)
            pos += n_acc + 1
            last = jnp.asarray([out[-1]], jnp.int32)
            # next round's anchor = token at the new pos-1, which is
            # chunk[0][n_acc] for every acceptance count
            prev = chunk[:, n_acc]
        return out[:n_new], target_calls

    return generate

"""Speculative decoding: draft-proposes, target-verifies, exact.

The standard two-model speedup for autoregressive decoding: a small
draft model proposes ``k`` tokens with cheap sequential steps, the large
target model scores all of them in ONE forward pass (sequential decode
becomes a parallel verify), and the longest accepted prefix is emitted
plus one more token. Two modes, both exact:

- **greedy** (``temperature == 0``): accept while the draft token equals
  the target argmax; the output is EXACTLY the target model's greedy
  sequence — acceptance only changes how many target forwards it takes,
  never the tokens (asserted by tests/test_speculative.py);
- **sampled** (``temperature > 0``): the rejection-resampling acceptance
  rule (`accept_resample`) — accept draft ``d`` with probability
  ``min(1, p(d)/q(d))``, resample the first rejection from
  ``normalize(max(p - q, 0))`` — under which every emitted token is
  distributed exactly as temperature-sampling the target, whatever the
  draft proposes (asserted statistically). With ``top_k``/``top_p``,
  BOTH target and draft distributions are truncated-and-renormalized
  (`decode.truncated_probs`) before the same rule: the theorem holds
  for any proposal, so emitted tokens are distributed exactly as the
  truncated target — i.e. exactly `make_generate`'s sampling.

TPU-static design: every device program has fixed shapes — the draft
proposal is a ``k``-step `lax.scan`, the verify is one ``k+1``-token
chunked forward (`make_forward_step`), and the data-dependent acceptance
length only travels to the host as a scalar. Rejected positions leave
stale K/V in both caches; that is safe for the same reason the serve
loop's padded prefill is: position ``p`` is rewritten exactly when the
real token at ``p`` is processed, and queries only attend positions
that have been rewritten.

Numerics: exactness vs `make_generate` holds bit-for-bit in float32
(asserted by tests). On TPU in bfloat16 the (k+1)-chunk verify rounds
differently than the reference's one-token steps (MXU results are
shape-dependent), so near-tie argmaxes can flip — the same documented
class as the serve loop's padded prefill and immaterial for trained
models. Acceptance is unaffected: a self-draft run on a real v5e hit
12 target calls for 48 tokens (ideal 11).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubegpu_tpu.workload.decode import (_select_token, init_cache,
                                         make_forward_step, truncated_probs,
                                         validate_sampling)
from kubegpu_tpu.workload.model import TransformerConfig


def accept_resample(p_rows, q_rows, drafts, key):
    """Rejection-resampling acceptance (the speculative-sampling rule).

    ``p_rows [k+1, V]``: target distribution after each chunk position;
    ``q_rows [k, V]``: the draft distribution each proposal was SAMPLED
    from; ``drafts [k]``. Accepts draft ``i`` with probability
    ``min(1, p_i(d_i) / q_i(d_i))``; on the first rejection emits a
    sample from ``normalize(max(p_i - q_i, 0))``; when all ``k`` are
    accepted emits a bonus sample from ``p_k``. Returns
    ``(n_acc, extra_token)`` — the emitted round is
    ``drafts[:n_acc] + [extra]``, and the theorem guarantees every
    emitted token is distributed EXACTLY as sampling the target
    (asserted statistically by tests/test_speculative.py)."""
    k = drafts.shape[0]
    ku, kr = jax.random.split(key)
    u = jax.random.uniform(ku, (k,))
    idx = jnp.arange(k)
    p_d = p_rows[idx, drafts]
    q_d = q_rows[idx, drafts]
    accept = u * q_d < p_d                       # u < p/q with q > 0
    n_acc = jnp.argmin(jnp.concatenate(
        [accept, jnp.array([False])]).astype(jnp.int32))
    # residual at the rejection point; plain p for the bonus position
    q_pad = jnp.concatenate([q_rows, jnp.zeros_like(p_rows[:1])])
    resid = jnp.maximum(p_rows[n_acc] - q_pad[n_acc], 0.0)
    mass = jnp.sum(resid)
    # p == q exactly cannot reject (u < 1), so mass > 0 on the reject
    # path mathematically; guard the float edge by falling back to p
    resid = jnp.where(mass > 1e-9, resid / jnp.maximum(mass, 1e-9),
                      p_rows[n_acc])
    extra = jax.random.categorical(kr, jnp.log(jnp.maximum(resid, 1e-30)))
    return n_acc, extra


def make_speculative_generate(target_cfg: TransformerConfig,
                              draft_cfg: TransformerConfig,
                              k: int = 4, mesh=None,
                              max_seq: int | None = None,
                              temperature: float = 0.0,
                              top_k: int = 0, top_p: float = 1.0):
    """Build ``generate(target_params, draft_params, prompt, n_new[, rng])
    -> (tokens [B=1 row list], target_calls)``.

    ``temperature == 0`` (default) is greedy speculative decoding —
    output EXACTLY the target's greedy sequence. ``temperature > 0`` is
    speculative SAMPLING with the rejection-resampling acceptance rule
    (`accept_resample`): every emitted token is distributed exactly as
    temperature-sampling the target, whatever the draft proposes. With
    ``top_k``/``top_p`` both target and draft rows are truncated and
    renormalized (`decode.truncated_probs`) before the same rule, which
    keeps the acceptance distribution-exact for the TRUNCATED target —
    exactly what `make_generate` samples. ``k`` is the draft lookahead
    per round. Both models must share the vocab.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    top_k = validate_sampling(target_cfg, temperature, top_k, top_p)
    sampling = temperature != 0.0
    max_seq = max_seq or min(target_cfg.max_seq, draft_cfg.max_seq)
    t_step = make_forward_step(target_cfg, mesh)
    d_step = make_forward_step(draft_cfg, mesh)

    def probs(logits):
        """[B?, V] -> truncated-and-renormalized sampling distribution
        (the full softmax when top_k/top_p are off)."""
        squeeze = logits.ndim == 1
        rows = logits[None, :] if squeeze else logits
        out = truncated_probs(rows, temperature, top_k, top_p)
        return out[0] if squeeze else out

    def prefill(params, step, cache, prompt, key):
        logits, cache = step(params, cache, prompt, 0)
        tok = _select_token(logits[:, -1, :], key, temperature, top_k,
                            top_p)
        return cache, tok

    # traced-shapes: prompt [1, T] int32 — T varies per prompt (one
    # trace per distinct prompt length; prefill runs once per generate)
    prefill_t = jax.jit(lambda p, c, x, s: prefill(p, t_step, c, x, s),
                        donate_argnums=(1,))
    # traced-shapes: prompt [1, T] int32 — varies, as prefill_t
    prefill_d = jax.jit(lambda p, c, x, s: prefill(p, d_step, c, x, s),
                        donate_argnums=(1,))

    def pick(logits, key):
        """Next token (and its full distribution row when sampling)."""
        if sampling:
            p = probs(logits)
            return jax.random.categorical(key, jnp.log(p)), p
        return jnp.argmax(logits, axis=-1), None

    def draft_propose(params, cache, prev, token, pos, key):
        """k draft proposals (greedy or sampled) from ``token`` at
        ``pos``; when sampling, also the ``[k, V]`` distributions each
        proposal was drawn from (the acceptance rule needs them).

        The first step processes the 2-token chunk ``[prev, token]`` at
        ``pos-1``: after a fully-accepted round the draft never
        processed its own k-th proposal, leaving a K/V hole at exactly
        ``pos-1`` — re-processing ``prev`` there fills the hole (and is
        an idempotent rewrite when no hole exists). Without this, the
        round after a full accept proposes against a zeroed cache row
        and acceptance collapses.

        NOTE: `serve.DecodeServer` carries this function's batched
        (per-slot) twin — both the per-round oracle jit and the fused
        multi-round device program (`spec_fused`), which also reuses
        `accept_resample` verbatim under `vmap`. Any change to the
        catch-up logic or the q-row plumbing must be mirrored there.
        The twins differ only in key lineage: this single-stream path
        folds the draft index into one caller key, while the server
        derives position-keyed per-slot keys so its streams are
        batching-invariant."""
        chunk = jnp.stack([prev, token], axis=1)        # [1, 2]
        logits, cache = d_step(params, cache, chunk, pos - 1)
        first, q0 = pick(logits[:, -1, :], jax.random.fold_in(key, 0))

        def body(carry, i):
            cache, tok, p = carry
            logits, cache = d_step(params, cache, tok[:, None], p)
            nxt, q = pick(logits[:, -1, :], jax.random.fold_in(key, i))
            out = (nxt, q[0]) if sampling else (nxt, jnp.zeros(()))
            return (cache, nxt, p + 1), out

        (cache, _, _), (toks, qs) = lax.scan(
            body, (cache, first, pos + 1), jnp.arange(1, k))
        drafts = jnp.concatenate([first, toks[:, 0]]) if k > 1 else first
        if sampling:
            q_rows = jnp.concatenate([q0, qs]) if k > 1 else q0
        else:
            q_rows = jnp.zeros(())
        return cache, drafts, q_rows  # [k], [k, V]

    # donate the caches: both loops rebind the returned cache, and an
    # undonated copy per round is pure overhead on the HBM-bandwidth-
    # bound decode path this module exists to speed up (serve.py donates
    # for the same reason)
    # traced-shapes: prev/token [1] int32, pos scalar int32, key [2]
    # uint32 — fixed; one trace per generate horizon
    draft_propose = jax.jit(draft_propose, donate_argnums=(1,))

    def verify(params, cache, chunk, pos):
        """One target forward over ``chunk [1, k+1]`` (last accepted
        token + k draft tokens) at ``pos``. Greedy: returns the target's
        greedy token after each position and the agreeing-prefix length.
        Sampling: returns the target's ``[k+1, V]`` distributions (the
        acceptance happens with the q_rows in `accept_resample`)."""
        logits, cache = t_step(params, cache, chunk, pos)
        if sampling:
            return cache, probs(logits[0]), jnp.int32(0)
        greedy = jnp.argmax(logits[0], axis=-1)           # [k+1]
        drafts = chunk[0, 1:]                             # [k]
        agree = drafts == greedy[:-1]
        n_acc = jnp.argmin(jnp.concatenate(
            [agree, jnp.array([False])]).astype(jnp.int32))
        return cache, greedy, n_acc

    # traced-shapes: chunk [1, k+1] int32, pos scalar int32 — fixed per
    # lookahead k; one trace per generate horizon
    verify = jax.jit(verify, donate_argnums=(1,))
    # traced-shapes: p_rows [k+1, V] f32, q_rows [k, V] f32, drafts [k]
    # int32, key [2] uint32 — fixed per lookahead k
    accept_jit = jax.jit(accept_resample)

    def generate(target_params, draft_params, prompt, n_new: int,
                 rng=None):
        if n_new < 1:
            raise ValueError(f"n_new must be >= 1, got {n_new}")
        if sampling and rng is None:
            raise ValueError("sampled speculative decode needs an rng key")
        if rng is None:
            rng = jax.random.PRNGKey(0)  # unused by greedy selection
        prompt = jnp.asarray(prompt, jnp.int32).reshape(1, -1)
        t0 = prompt.shape[1]
        if t0 + n_new + k + 1 > max_seq:
            raise ValueError(
                f"prompt ({t0}) + n_new ({n_new}) + lookahead ({k + 1}) "
                f"exceeds max_seq ({max_seq})")
        # Horizon-sized caches, exactly as decode.make_generate: the
        # full-cache attention read is the HBM traffic that bounds
        # decode, and positions past this call's reach contribute zero.
        # +k+1: verify may write up to k+1 positions past the last
        # emitted token before truncation.
        horizon = min(max_seq, -(-(t0 + n_new + k + 1) // 128) * 128)
        t_cache = init_cache(target_cfg, 1, horizon)
        d_cache = init_cache(draft_cfg, 1, horizon)
        t_cache, first = prefill_t(target_params, t_cache, prompt,
                                   jax.random.fold_in(rng, 0))
        d_cache, _ = prefill_d(draft_params, d_cache, prompt,
                               jax.random.fold_in(rng, 1))

        out = [int(np.asarray(first)[0])]
        pos = t0            # both caches hold [0, t0); `first` unprocessed
        target_calls = 1
        last = first        # [1] last accepted-but-unprocessed token
        prev = prompt[:, -1]  # token at pos-1 (draft catch-up anchor)
        rounds = 0
        while len(out) < n_new:
            rounds += 1
            rkey = jax.random.fold_in(rng, 1 + rounds)
            d_cache, drafts, q_rows = draft_propose(
                draft_params, d_cache, prev, last, jnp.int32(pos), rkey)
            chunk = jnp.concatenate([last, drafts]).reshape(1, k + 1)
            t_cache, tout, n_acc = verify(target_params, t_cache, chunk,
                                          jnp.int32(pos))
            target_calls += 1
            # ONE host transfer per round: on a remote-TPU rig every
            # device_get pays the tunnel RTT, and three sequential
            # fetches per round tripled the loop's latency floor
            if sampling:
                n_acc, extra = accept_jit(
                    tout, q_rows, drafts,
                    jax.random.fold_in(rkey, 10_000))
                # host-sync: allowed -- one batched transfer per round
                # (acceptance length decides the host-side loop bound)
                n_acc, extra_tok, drafts_np = jax.device_get(
                    (n_acc, extra, drafts))
                n_acc = int(n_acc)
                extra_tok = int(extra_tok)
            else:
                # host-sync: allowed -- one batched transfer per round
                # (acceptance length decides the host-side loop bound)
                n_acc, tout_np, drafts_np = jax.device_get(
                    (n_acc, tout, drafts))
                n_acc = int(n_acc)
                extra_tok = int(tout_np[n_acc])
            # accepted draft tokens, then the correction-or-bonus token
            new = [int(x) for x in drafts_np[:n_acc]] + [extra_tok]
            out.extend(new)
            pos += n_acc + 1
            last = jnp.asarray([out[-1]], jnp.int32)
            # next round's anchor = token at the new pos-1, which is
            # chunk[0][n_acc] for every acceptance count
            prev = chunk[:, n_acc]
        return out[:n_new], target_calls

    return generate

"""Named model-family presets.

One place that spells out the families the workload layer supports, at
demo-able sizes — each is a `TransformerConfig` the train step, decode
path, dryrun mesh, and `cmd/train_demo.py --preset` all accept:

- ``dense``       — the flagship decoder-only transformer (MHA, SwiGLU);
- ``gqa``         — grouped-query attention (narrow KV cache/projections);
- ``windowed``    — sliding-window attention (Mistral-style long context:
                    O(T*window) attention, range grows with depth);
- ``moe``         — mixture-of-experts FFN, Mixtral-style top-2 routed,
                    experts sharded over the model axis (expert
                    parallelism);
- ``long-ring``   — ring-attention configuration for sequence-parallel
                    meshes (seq axis > 1), full causal span;
- ``long-ulysses``— Ulysses all-to-all sequence parallelism.

The reference has no training runtime at all (SURVEY.md §0); these are
the TPU build's workload families, every one exercised by tests.
"""

from __future__ import annotations

from typing import Any, Dict

from kubegpu_tpu.workload.model import TransformerConfig

_BASE: Dict[str, Any] = dict(vocab=512, d_model=128, n_heads=8,
                             n_layers=2, d_ff=384, max_seq=512)

PRESETS: Dict[str, Dict[str, Any]] = {
    "dense": dict(_BASE),
    "gqa": dict(_BASE, n_kv_heads=2),
    "windowed": dict(_BASE, attn_window=64),
    "moe": dict(_BASE, n_experts=4, moe_top_k=2),  # Mixtral-style top-2
    "long-ring": dict(_BASE, seq_impl="ring"),
    "long-ulysses": dict(_BASE, seq_impl="ulysses"),
}


def preset_names() -> list[str]:
    return sorted(PRESETS)


def make_config(name: str, **overrides: Any) -> TransformerConfig:
    """Build a preset's config; keyword overrides win (e.g. d_model)."""
    if name not in PRESETS:
        raise KeyError(
            f"unknown preset {name!r}; known: {', '.join(preset_names())}")
    return TransformerConfig(**{**PRESETS[name], **overrides})

"""Pallas TPU kernels for the workload layer's hot ops."""

from kubegpu_tpu.workload.kernels.flash import flash_attention

__all__ = ["flash_attention"]

"""Fused causal (flash) attention as a Pallas TPU kernel.

The workload layer's hottest op. XLA's fused attention is good; this kernel
keeps the whole online-softmax loop in VMEM with f32 accumulators and never
materializes the [T, T] score matrix in HBM — the standard flash recurrence
tiled to the MXU:

- grid ``(B, H, q_blocks, k_blocks)``; the last grid dimension runs
  sequentially on a TensorCore, so per-q-block accumulators (``acc``, ``m``,
  ``l``) live in VMEM scratch across k-steps and the output is written once
  on the final k-step;
- fully-masked causal blocks are skipped (`pl.when`), halving work for the
  causal case;
- backward is the standard two-kernel flash backward (dq swept over k blocks,
  dk/dv swept over q blocks) off saved ``(o, lse)`` residuals — no [T, T]
  matrix in the backward either;
- ``q_offset``/``kv_offset`` place the local blocks at *global* sequence
  positions so the same kernel serves ring attention's rotating K/V blocks
  (`kubegpu_tpu.workload.ring`), where offsets are traced values derived
  from `lax.axis_index`.

The reference schedules accelerator jobs but has no compute path at all
(SURVEY.md §0); this kernel exists because the TPU build ships the workload
layer too. Numerics match `model._causal_attention` to float tolerance
(tests/test_kernels.py, interpret mode on CPU).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128  # TPU lane width; m/l scratch carries the row stat in every lane


@dataclass(frozen=True)
class _Cfg:
    """Static kernel configuration (hashable: rides in nondiff_argnums)."""

    scale: float
    causal: bool
    block_q: int
    block_k: int
    interpret: bool
    # sliding window: row q attends keys in (q-window, q]; 0 = unlimited.
    # Fully-out-of-window k-blocks are skipped like fully-masked causal
    # ones, so long sequences pay O(T*window), not O(T^2).
    window: int = 0


def _pick_block(t: int, head_dim: int = 64) -> int:
    """Largest block that divides ``t``, capped by a VMEM-aware bound.

    Tuned on a real v5e (tools/tune_flash.py, B=8 T=2048 H=16 D=64,
    fwd+bwd): 1024-blocks run 10.99 ms vs 49.1 ms for the old 128-block
    default and 23.1 ms for XLA's fused attention. Re-confirmed at the
    SHIPPED headline shape (B=4 T=2048 H=18 D=128, fwd+bwd):
    1024x1024 blocks run 7.28 ms vs 27.8 ms for 128-blocks and
    14.6 ms for XLA — the same ranking at double the head width, so
    the D<=128 cap keeping the full 1024 is right. Small blocks lose
    because the grid enumerates ALL (qi, ki) pairs — skipped tiles still
    pay the grid step and block DMA — so the step count grows
    quadratically as blocks shrink. That also holds for sliding-window
    sparsity: at T=16384 window=64, 1024-blocks run 51.5 ms vs 517 ms
    for 128-blocks (10x) even though the small blocks touch 1/16 the
    FLOPs. 2048-blocks fail to compile (VMEM); wider head dims scale
    every tile linearly, so the cap halves as head_dim doubles past 128."""
    cap = 1024 if head_dim <= 128 else max(128, 1024 * 128 // head_dim)
    cap = 1 << (cap.bit_length() - 1)  # power of two, or the halving
    b = min(cap, t)                    # chain below can skip divisors of t
    while b > 8 and t % b:
        b //= 2
    return b if t % b == 0 else t


def _pos(off_ref, which: int, block_i: int, block: int, shape, axis: int):
    """Global positions for a q (axis 0) / kv (axis 1) block as a 2-D iota."""
    base = off_ref[0, which] + block_i * block
    return base + lax.broadcasted_iota(jnp.int32, shape, axis)


def _block_visible(cfg: _Cfg, off_ref, qi, ki):
    """False iff the causal/window mask hides the whole (qi, ki) tile."""
    if not cfg.causal and not cfg.window:
        return True
    q_min = off_ref[0, 0] + qi * cfg.block_q
    q_max = q_min + cfg.block_q - 1
    kv_min = off_ref[0, 1] + ki * cfg.block_k
    kv_max = kv_min + cfg.block_k - 1
    # past the early return at least one bound applies, and a window's
    # upper bound IS the causal bound (keys newer than q are outside
    # (q - window, q] by definition)
    vis = q_max >= kv_min
    if cfg.window:
        # the tile's newest key must still be inside the OLDEST query
        # row's window (q - window, q]
        vis = jnp.logical_and(vis, kv_max > q_min - cfg.window)
    return vis


def _tile_mask(cfg: _Cfg, off_ref, qi, ki):
    """The (block_q, block_k) visibility mask at global positions, or
    None when nothing is masked."""
    if not cfg.causal and not cfg.window:
        return None
    shp = (cfg.block_q, cfg.block_k)
    qpos = _pos(off_ref, 0, qi, cfg.block_q, shp, 0)
    kpos = _pos(off_ref, 1, ki, cfg.block_k, shp, 1)
    # past the early return at least one bound applies, and window
    # implies the causal upper bound — (q - window, q] excludes future
    # keys by definition, with or without the causal flag
    mask = qpos >= kpos
    if cfg.window:
        mask = jnp.logical_and(mask, kpos > qpos - cfg.window)
    return mask


def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, cfg: _Cfg, num_k: int):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(_block_visible(cfg, off_ref, qi, ki))
    def _compute():
        # Dots take the NATIVE (bf16) operands with f32 ACCUMULATION —
        # the MXU's native mode. Casting operands to f32 first would run
        # every matmul at 1/4 the bf16 rate; the accumulator precision is
        # identical either way (preferred_element_type=f32).
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * cfg.scale
        mask = _tile_mask(cfg, off_ref, qi, ki)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev,
                            jnp.broadcast_to(jnp.max(s, 1, keepdims=True),
                                             m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_ref[...] = l_ref[...] * alpha + jnp.broadcast_to(
            jnp.sum(p, 1, keepdims=True), m_prev.shape)
        m_ref[...] = m_new
        pv = lax.dot_general(p.astype(v_ref.dtype), v_ref[0, 0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_ref[...][:, :1]
        o_ref[0, 0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # lse = m + log(l), lane-broadcast (TPU wants a 128-lane minor dim);
        # -inf rows (nothing visible) stay hugely negative.
        lse_ref[0, 0] = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))


def _fwd(cfg: _Cfg, offsets, q, k, v):
    """q,k,v: [B,H,T,D] → (o [B,H,Tq,D], lse [B,H,Tq,LANES] lane-broadcast)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    num_q, num_k = tq // cfg.block_q, tk // cfg.block_k
    grid = (b, h, num_q, num_k)

    def qmap(bi, hi, qi, ki):
        return (bi, hi, qi, 0)

    def kmap(bi, hi, qi, ki):
        return (bi, hi, ki, 0)

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg=cfg, num_k=num_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, cfg.block_q, d), qmap),
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap),
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, cfg.block_q, d), qmap),
            pl.BlockSpec((1, 1, cfg.block_q, LANES), qmap),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, d), jnp.float32),
            pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
            pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(offsets, q, k, v)
    return o, lse


def _dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, cfg: _Cfg, num_k: int):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(_block_visible(cfg, off_ref, qi, ki))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * cfg.scale
        mask = _tile_mask(cfg, off_ref, qi, ki)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, :1])
        do = do_ref[0, 0]
        dp = lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, :1])
        dq_acc[...] += cfg.scale * lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, cfg: _Cfg, num_q: int):
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(_block_visible(cfg, off_ref, qi, ki))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * cfg.scale
        mask = _tile_mask(cfg, off_ref, qi, ki)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, :1])
        do = do_ref[0, 0]
        dv_acc[...] += lax.dot_general(p.astype(do.dtype), do,
                                       (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, :1])
        dk_acc[...] += cfg.scale * lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(cfg: _Cfg, offsets, q, k, v, o, lse, do, dlse):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    num_q, num_k = tq // cfg.block_q, tk // cfg.block_k

    # delta_i = rowsum(dO_i * O_i): tiny elementwise pass, XLA fuses it;
    # lane-broadcast like lse so the kernels read a (block_q, LANES) tile.
    # An lse cotangent folds in exactly here: dS = P∘(dP - delta) + dlse∘P
    # = P∘(dP - (delta - dlse)) — ring attention's partial-merge weights
    # differentiate through lse, so this term is load-bearing there.
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1, keepdims=True), (b, h, tq, LANES))
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    def qmap(bi, hi, qi, ki):
        return (bi, hi, qi, 0)

    def kmap(bi, hi, qi, ki):
        return (bi, hi, ki, 0)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg, num_k=num_k),
        grid=(b, h, num_q, num_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, cfg.block_q, d), qmap),
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap),
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap),
            pl.BlockSpec((1, 1, cfg.block_q, d), qmap),
            pl.BlockSpec((1, 1, cfg.block_q, LANES), qmap),
            pl.BlockSpec((1, 1, cfg.block_q, LANES), qmap),
        ],
        out_specs=pl.BlockSpec((1, 1, cfg.block_q, d), qmap),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((cfg.block_q, d), jnp.float32)],
        interpret=cfg.interpret,
    )(offsets, q, k, v, do, lse, delta)

    # dk/dv: sweep q blocks in the sequential (last) grid dimension.
    def kmap2(bi, hi, ki, qi):
        return (bi, hi, ki, 0)

    def qmap2(bi, hi, ki, qi):
        return (bi, hi, qi, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg=cfg, num_q=num_q),
        grid=(b, h, num_k, num_q),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, cfg.block_q, d), qmap2),
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap2),
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap2),
            pl.BlockSpec((1, 1, cfg.block_q, d), qmap2),
            pl.BlockSpec((1, 1, cfg.block_q, LANES), qmap2),
            pl.BlockSpec((1, 1, cfg.block_q, LANES), qmap2),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap2),
            pl.BlockSpec((1, 1, cfg.block_k, d), kmap2),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(offsets, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Cfg, offsets, q, k, v):
    return _fwd(cfg, offsets, q, k, v)


def _flash_fwd(cfg: _Cfg, offsets, q, k, v):
    from jax.ad_checkpoint import checkpoint_name

    o, lse = _fwd(cfg, offsets, q, k, v)
    # Name the residuals so a rematerialisation policy can SAVE them
    # (model.py's "dots" policy does): without this, jax.checkpoint must
    # re-run the whole forward kernel in the backward pass just to
    # regenerate (o, lse) for the custom VJP.
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return (o, lse), (offsets, q, k, v, o, lse)


def _flash_bwd(cfg: _Cfg, res, cts):
    offsets, q, k, v, o, lse = res
    do, dlse = cts
    dq, dk, dv = _bwd(cfg, offsets, q, k, v, o, lse, do, dlse)
    d_off = np.zeros(offsets.shape, jax.dtypes.float0)  # int primal
    return d_off, dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_with_lse(q, k, v, scale, *, q_offset=0, kv_offset=0,
                             causal=True, block_q=None, block_k=None,
                             interpret=False, window=0):
    """Flash attention returning ``(out, lse)``.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]. ``lse`` is [B, H, Tq] — the
    log-sum-exp of each row's visible scores, which makes partial results
    from disjoint K/V shards mergeable (`merge_partials`), the hook ring
    attention uses. Offsets may be traced ints (global positions =
    offset + local index). ``window`` > 0 restricts each row to the
    newest ``window`` keys (sliding-window attention); fully-out-of-
    window tiles are skipped, so cost is O(Tq * window).
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    cfg = _Cfg(scale=float(scale), causal=bool(causal),
               block_q=block_q or _pick_block(tq, d),
               block_k=block_k or _pick_block(tk, d),
               interpret=bool(interpret), window=int(window))
    if tq % cfg.block_q or tk % cfg.block_k:
        raise ValueError(f"seq lens ({tq}, {tk}) not divisible by blocks "
                         f"({cfg.block_q}, {cfg.block_k})")
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32),
         jnp.asarray(kv_offset, jnp.int32)]).reshape(1, 2)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o, lse = _flash(cfg, offsets, qt, kt, vt)
    return o.transpose(0, 2, 1, 3), lse[..., 0]


def flash_attention(q, k, v, scale, **kw):
    """Flash attention: [B, T, H, D] in, [B, T, H, D] out."""
    return flash_attention_with_lse(q, k, v, scale, **kw)[0]


def merge_partials(o1, lse1, o2, lse2):
    """Combine attention over two disjoint K/V sets from their (o, lse)
    partials: o = softmax-weighted mix, lse = log(exp(lse1)+exp(lse2)).
    Associative — ring attention folds rotating blocks with it.
    o: [B, T, H, D]; lse: [B, H, T]."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    lse = m + jnp.log(w1 + w2)
    # [B,H,T] → [B,T,H,1] to weight [B,T,H,D]
    def wgt(w):
        return w.transpose(0, 2, 1)[..., None]

    denom = wgt(w1 + w2)
    o = (o1.astype(jnp.float32) * wgt(w1)
         + o2.astype(jnp.float32) * wgt(w2)) / jnp.maximum(denom, 1e-30)
    return o.astype(o1.dtype), lse

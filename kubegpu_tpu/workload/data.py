"""Input pipeline: token shards + a prefetching loader.

The data-loader half of the native runtime (`native/dataloader.cpp`):
mmap'd binary token shards read by a C++ producer thread into a bounded
prefetch ring, so host IO overlaps device compute. The reference has no
training runtime at all (SURVEY.md §0); its one native seam was an
external discovery daemon (§2.9) — here the same native-behind-a-seam
pattern feeds the workload layer.

`PyTokenLoader` is the pure-Python semantic reference (identical
sampling contract, differentially tested bit-for-bit in
tests/test_dataloader.py); `NativeTokenLoader` is the C++ fast path;
`make_loader` picks whichever is available.

Shard format: 8-byte magic ``KGTDSH01``, uint64 LE token count, then
uint32 LE tokens. Sampling: splitmix64 from ``seed``; per sample
``shard = next() % n_shards`` then ``start = next() % (len - seq1 + 1)``;
``batch`` samples per batch, row order.
"""

from __future__ import annotations

import ctypes
import struct

import numpy as np

MAGIC = b"KGTDSH01"
_MASK = (1 << 64) - 1


def write_token_shard(path: str, tokens) -> str:
    """Write a uint32 token array as one shard file."""
    arr = np.asarray(tokens, dtype=np.uint32)
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<Q", arr.size))
        f.write(arr.tobytes())
    return path


def read_token_shard(path: str) -> np.ndarray:
    """Validated mmap of one shard's tokens (zero-copy)."""
    with open(path, "rb") as f:
        header = f.read(16)
    if len(header) < 16 or header[:8] != MAGIC:
        raise ValueError(f"{path}: not a KGTDSH01 token shard")
    (n,) = struct.unpack("<Q", header[8:16])
    arr = np.memmap(path, dtype=np.uint32, mode="r", offset=16)
    if arr.size < n:
        raise ValueError(f"{path}: truncated shard ({arr.size} < {n})")
    return arr[:n]


class _SplitMix64:
    """Must match dataloader.cpp's SplitMix64 exactly."""

    def __init__(self, seed: int):
        self.x = seed & _MASK

    def next(self) -> int:
        self.x = (self.x + 0x9E3779B97F4A7C15) & _MASK
        z = self.x
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
        return z ^ (z >> 31)


class PyTokenLoader:
    """Pure-Python loader — the semantic reference for the native one."""

    def __init__(self, paths: list, batch: int, seq_len: int, seed: int = 0):
        if not paths:
            raise ValueError("no shards")
        self.shards = [read_token_shard(p) for p in paths]
        self.batch = int(batch)
        self.seq1 = int(seq_len) + 1  # inputs + next-token target
        for p, s in zip(paths, self.shards):
            if s.size < self.seq1:
                raise ValueError(f"shard {p} shorter than sequence length")
        self.rng = _SplitMix64(seed)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        out = np.empty((self.batch, self.seq1), np.int32)
        for b in range(self.batch):
            shard = self.shards[self.rng.next() % len(self.shards)]
            start = self.rng.next() % (shard.size - self.seq1 + 1)
            out[b] = shard[start:start + self.seq1].astype(np.int32)
        return out

    def close(self) -> None:
        pass


class NativeTokenLoader:
    """C++ loader: mmap + prefetch thread (`native/dataloader.cpp`)."""

    def __init__(self, paths: list, batch: int, seq_len: int, seed: int = 0,
                 prefetch: int = 2):
        from kubegpu_tpu import native

        lib = native.get_lib()
        if lib is None or not hasattr(lib, "dl_open"):
            raise RuntimeError("native data loader unavailable "
                               "(build with `make -C native`)")
        self._lib = lib
        self.batch = int(batch)
        self.seq1 = int(seq_len) + 1
        self._handle = lib.dl_open("\n".join(paths).encode(),
                                   self.batch, self.seq1, seed, prefetch)
        if not self._handle:
            raise RuntimeError(
                f"dl_open: {lib.dl_last_error().decode()}")
        self._buf = np.empty(self.batch * self.seq1, np.int32)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        n = self._lib.dl_next(
            self._handle,
            self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._buf.size)
        if n < 0:
            raise RuntimeError(
                f"dl_next: {self._lib.dl_last_error().decode()}")
        return self._buf[:n].reshape(self.batch, self.seq1).copy()

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dl_close(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


def make_loader(paths: list, batch: int, seq_len: int, seed: int = 0):
    """Native loader when built, Python fallback otherwise — same stream
    either way (the sampling contract is differentially tested)."""
    try:
        return NativeTokenLoader(paths, batch, seq_len, seed)
    except RuntimeError:
        return PyTokenLoader(paths, batch, seq_len, seed)

"""Mesh construction and sharding layout.

Axis convention:

- ``data``  — batch (pure data parallel; gradients all-reduce here)
- ``model`` — tensor parallel (attention heads / FFN columns)
- ``seq``   — sequence/context parallel (ring attention rides this axis)

``mesh_from_env`` consumes the runtime-hook contract
(`kubegpu_tpu.node.manager`): ``TPU_VISIBLE_CHIPS`` tells the process which
chips it owns; the mesh is laid out so the ``model``/``seq`` axes map to
ICI neighbors (the scheduler guaranteed contiguity) and ``data`` to the
outermost dimension.
"""

from __future__ import annotations

import os

import numpy as np

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"


def _factor3(n: int) -> tuple:
    """Factor n into (dp, sp, tp): tp innermost (fastest-varying devices =
    tightest ICI neighbors), then sp, then dp."""
    tp = 1
    for cand in (8, 4, 2):
        if n % cand == 0:
            tp = cand
            break
    rest = n // tp
    sp = 1
    for cand in (4, 2):
        if rest % cand == 0:
            sp = cand
            break
    dp = rest // sp
    return dp, sp, tp


def make_mesh(n_devices: int | None = None, dp: int | None = None,
              sp: int | None = None, tp: int | None = None, devices=None):
    """Build a (data, seq, model) mesh over the first n visible devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None or sp is None or tp is None:
        dp, sp, tp = _factor3(n_devices)
    if dp * sp * tp != n_devices:
        raise ValueError(f"dp*sp*tp={dp * sp * tp} != n_devices={n_devices}")
    arr = np.array(devices).reshape(dp, sp, tp)
    return Mesh(arr, (AXIS_DATA, AXIS_SEQ, AXIS_MODEL))


def distributed_init_from_env(env: dict | None = None) -> bool:
    """Form the cross-host process group the runtime hook described.

    The other half of the placement contract (SURVEY.md §2.9: "hand an
    8-chip JAX job an ICI-contiguous slice with correct chip
    visibility"): a gang-scheduled pod's hook-rewritten config carries

    - ``TPU_COORDINATOR_ADDRESS`` — host:port of the gang's rank-0 pod
    - ``TPU_PROCESS_COUNT``      — number of pods in the gang
    - ``TPU_PROCESS_ID``         — this pod's rank (gang member order)

    and calling `jax.distributed.initialize` with exactly those values
    joins every member into ONE JAX process group, so ``jax.devices()``
    becomes the global slice and `make_mesh` lays the mesh over all of
    it. Returns True when a multi-process group was formed; single-
    process runs (env absent or count 1) return False untouched, so
    every workload binary can call this unconditionally."""
    env = env if env is not None else os.environ
    addr = env.get("TPU_COORDINATOR_ADDRESS", "")
    count = int(env.get("TPU_PROCESS_COUNT", "1") or 1)
    if not addr or count <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=addr, num_processes=count,
        process_id=int(env.get("TPU_PROCESS_ID", "0") or 0))
    return True


def mesh_from_env(env: dict | None = None):
    """Mesh for the chips this container was allocated (runtime-hook env).

    In a multi-process group (after `distributed_init_from_env`) the
    env names only LOCAL chips; the mesh must span the whole gang's
    devices, so the global device count wins there."""
    env = env if env is not None else os.environ
    import jax

    if jax.process_count() > 1:
        return make_mesh(len(jax.devices()))
    visible = env.get("TPU_VISIBLE_CHIPS", "")
    n = len([c for c in visible.split(",") if c]) if visible else None
    return make_mesh(n)


def global_batch(mesh, np_batch):
    """Shard one host-replicated numpy batch over the mesh's data axis.

    Every process holds the SAME full global batch (deterministic
    loaders seeded identically — the loader contract); each device
    materializes only its slice. Single-process this is a plain
    device_put; multi-process it is the only correct way to feed a jit
    whose arguments span processes (a process-local ``jnp.asarray``
    cannot be addressed by a global sharding)."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, batch_pspec())
    return jax.make_array_from_callback(
        np.shape(np_batch), sharding, lambda idx: np_batch[idx])


def batch_pspec():
    from jax.sharding import PartitionSpec as P

    return P(AXIS_DATA, None)


def activation_pspec():
    from jax.sharding import PartitionSpec as P

    return P(AXIS_DATA, AXIS_SEQ, None)


def param_pspecs(cfg):
    """PartitionSpec pytree matching ``model.init_params`` exactly.

    Tensor-parallel layout: column-parallel in (qkv, FFN up), row-parallel
    out (attn out, FFN down) — one psum per block, inserted by GSPMD.
    """
    from jax.sharding import PartitionSpec as P

    layer = {
        "ln1": P(None),
        "wq": P(None, AXIS_MODEL),
        "wk": P(None, AXIS_MODEL),
        "wv": P(None, AXIS_MODEL),
        "wo": P(AXIS_MODEL, None),
        "ln2": P(None),
    }
    if getattr(cfg, "n_experts", 0) > 0:
        from kubegpu_tpu.workload.moe import moe_pspecs

        layer["moe"] = moe_pspecs(AXIS_MODEL)
    else:
        layer.update({
            "w_up": P(None, AXIS_MODEL),
            "w_gate": P(None, AXIS_MODEL),
            "w_down": P(AXIS_MODEL, None),
        })
    return {
        "embed": P(None, None),
        "unembed": P(None, AXIS_MODEL),
        "final_norm": P(None),
        "layers": [
            {k: (dict(v) if isinstance(v, dict) else v) for k, v in layer.items()}
            for _ in range(cfg.n_layers)
        ],
    }

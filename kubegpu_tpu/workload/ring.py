"""Ring attention: exact causal attention over a sequence-parallel axis.

Each shard holds a block of the sequence; K/V blocks rotate around the ring
via `lax.ppermute` while every shard accumulates attention for its local Q
block with an online (flash-style) softmax — full O(T^2) attention without
ever materializing the full sequence on one chip. Communication is
neighbor-to-neighbor, so it rides ICI links — exactly the pattern the
scheduler's contiguity guarantee exists for.

Matches non-ring causal attention bit-for-bit up to float tolerance (see
tests/test_workload.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, scale: float,
                   window: int = 0):
    """Causal multi-head attention with K/V rotating around ``axis_name``.

    q, k, v: per-shard blocks ``[B, T_local, H, D]`` (already RoPE'd with
    global positions). Returns ``[B, T_local, H, D]``. ``window`` > 0 =
    sliding-window attention over GLOBAL positions (each row attends the
    newest ``window`` keys), masked per rotating block exactly like the
    single-shard path.
    """
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_pos = my_index * t_local + jnp.arange(t_local)

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def attend(acc, k_blk, v_blk, r):
        """One online-softmax accumulation against the block from shard
        (my_index - r)."""
        o, m, l = acc
        src = (my_index - r) % axis_size
        kv_pos = src * t_local + jnp.arange(t_local)

        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32)) * scale
        causal = q_pos[:, None] >= kv_pos[None, :]
        if window:
            causal &= kv_pos[None, :] > q_pos[:, None] - window
        s = jnp.where(causal[None, None, :, :], s, NEG_INF)

        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        return o_new, m_new, l_new

    def step(carry, r):
        o, m, l, k_blk, v_blk = carry
        o, m, l = attend((o, m, l), k_blk, v_blk, r)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    b, _, h, d = q.shape
    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    # Rotate only between steps: the last block needs no onward ppermute,
    # so scan axis_size-1 rotating steps, then accumulate the final block.
    (o, m, l, k_last, v_last), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(max(0, axis_size - 1)))
    o, m, l = attend((o, m, l), k_last, v_last, axis_size - 1)

    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_flash_attention(q, k, v, axis_name: str, scale: float,
                         interpret: bool = False, window: int = 0):
    """Ring attention whose per-step block attend is the Pallas flash
    kernel (`kernels.flash`): each rotating K/V block is attended with
    global-position causal masking (offsets = shard indices × block len),
    and the normalized partials merge by lse arithmetic. Same recurrence
    as `ring_attention`, with the inner loop on the MXU via Pallas."""
    from kubegpu_tpu.workload.kernels.flash import (
        flash_attention_with_lse, merge_partials)

    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    t_local = q.shape[1]
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def attend(acc, k_blk, v_blk, r):
        o, lse = acc
        src = (my_index - r) % axis_size
        o_r, lse_r = flash_attention_with_lse(
            q, k_blk, v_blk, scale, q_offset=my_index * t_local,
            kv_offset=src * t_local, causal=True, interpret=interpret,
            window=window)
        return merge_partials(o, lse, o_r, lse_r)

    def step(carry, r):
        o, lse, k_blk, v_blk = carry
        o, lse = attend((o, lse), k_blk, v_blk, r)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o, lse, k_nxt, v_nxt), None

    b, t, h, d = q.shape
    # float32 accumulator across steps (merge_partials keeps the carry's
    # dtype) — matches ring_attention's f32 carry; cast once at the end.
    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t), NEG_INF, jnp.float32)
    (o, lse, k_last, v_last), _ = lax.scan(
        step, (o0, lse0, k, v), jnp.arange(max(0, axis_size - 1)))
    o, _ = attend((o, lse), k_last, v_last, axis_size - 1)
    return o.astype(q.dtype)


def make_sharded_ring_attention(mesh, data_axis: str, seq_axis: str,
                                model_axis: str, scale: float,
                                use_flash: bool = False,
                                interpret: bool = False, window: int = 0):
    """shard_map wrapper: GSPMD handles the rest of the model; attention
    drops to per-shard code so the ring's ppermutes are explicit.
    ``use_flash`` swaps the per-step attend onto the Pallas kernel."""
    from jax.sharding import PartitionSpec as P

    spec = P(data_axis, seq_axis, model_axis, None)

    def fn(q, k, v):
        if use_flash:
            return ring_flash_attention(q, k, v, seq_axis, scale,
                                        interpret=interpret, window=window)
        return ring_attention(q, k, v, seq_axis, scale, window=window)

    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)

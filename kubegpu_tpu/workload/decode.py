"""Autoregressive inference: KV-cache prefill + decode, sharded.

The serving half of the workload layer (training lives in `train.py`).
TPU-first design:

- **Static shapes**: the KV cache is allocated at ``max_seq`` up front and
  written with `lax.dynamic_update_slice`; attention always reads the full
  cache with a position mask, so every decode step compiles to the same
  program (no shape-driven recompiles).
- **Token loop inside jit**: `make_generate` runs the whole greedy decode
  as one `lax.scan`, not a Python loop — one compilation, no host↔device
  round-trip per token.
- **Sharding**: batch on ``data``, heads on ``model`` (the cache is
  sharded the same way); decode chunks are tiny so the ``seq`` axis is
  unused here — GSPMD inserts the same per-layer collectives as training.

Matches `model.make_forward` logits exactly (same weights, same RoPE
positions) — asserted by test.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from kubegpu_tpu.workload import spmd
from kubegpu_tpu.workload.model import (TransformerConfig, _rmsnorm, _rope)

NEG_INF = -1e30


def init_cache(cfg: TransformerConfig, batch: int, max_seq: int):
    """Zeroed per-layer KV cache: list of {"k","v"} of
    ``[B, max_seq, KV_H, D]`` in the compute dtype. With GQA
    (``cfg.n_kv_heads``) the cache is n_heads/kv_heads smaller — the
    decode-bandwidth saving the variant exists for."""
    dt = cfg.compute_dtype()
    shape = (batch, max_seq, cfg.kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            for _ in range(cfg.n_layers)]


def cache_pspecs(cfg: TransformerConfig, mesh=None):
    """PartitionSpec pytree matching `init_cache`: batch on data, KV
    heads on model when they divide the model-axis size (mirrors the kv
    weight sharding); a narrow GQA/MQA cache whose kv_heads the mesh
    cannot split is replicated on that axis instead of crashing."""
    from jax.sharding import PartitionSpec as P

    head_axis = spmd.AXIS_MODEL
    if mesh is not None:
        tp = mesh.shape.get(spmd.AXIS_MODEL, 1)
        if tp > 1 and cfg.kv_heads % tp:
            head_axis = None
    spec = P(spmd.AXIS_DATA, None, head_axis, None)
    return [{"k": spec, "v": spec} for _ in range(cfg.n_layers)]


def make_forward_step(cfg: TransformerConfig, mesh=None):
    """Build ``step(params, cache, tokens, start_pos) ->
    (logits, new_cache)``: process a chunk of ``tokens [B, T]`` whose
    first token sits at absolute position ``start_pos``, attending over
    everything cached so far plus the chunk itself. Used with T=prompt
    length for prefill and T=1 for decode."""

    def constrain(x, *spec):
        if mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))

    def step(params, cache, tokens, start_pos):
        dt = cfg.compute_dtype()
        b, t = tokens.shape
        s_max = cache[0]["k"].shape[1]
        scale = cfg.head_dim ** -0.5
        x = params["embed"].astype(dt)[tokens]
        x = constrain(x, spmd.AXIS_DATA, None, None)
        positions = start_pos + jnp.broadcast_to(jnp.arange(t), (b, t))
        # chunk position i attends cache positions <= start_pos + i
        # (and, with a sliding window, only the newest window of them)
        kv_pos = jnp.arange(s_max)
        q_pos = (start_pos + jnp.arange(t))[:, None]
        mask = kv_pos[None, :] <= q_pos
        if cfg.attn_window:
            mask &= kv_pos[None, :] > q_pos - cfg.attn_window

        new_cache = []
        for layer, kv in zip(params["layers"], cache):
            h = _rmsnorm(x, layer["ln1"])
            q = (h @ layer["wq"].astype(dt)).reshape(b, t, cfg.n_heads,
                                                     cfg.head_dim)
            k = (h @ layer["wk"].astype(dt)).reshape(b, t, cfg.kv_heads,
                                                     cfg.head_dim)
            v = (h @ layer["wv"].astype(dt)).reshape(b, t, cfg.kv_heads,
                                                     cfg.head_dim)
            q = _rope(q, positions, cfg.rope_theta)
            k = _rope(k, positions, cfg.rope_theta)
            ck = lax.dynamic_update_slice(kv["k"], k.astype(dt),
                                          (0, start_pos, 0, 0))
            cv = lax.dynamic_update_slice(kv["v"], v.astype(dt),
                                          (0, start_pos, 0, 0))
            new_cache.append({"k": ck, "v": cv})

            # bf16 operands, f32 accumulation — MXU-native (see
            # model._causal_attention). With GQA the query heads are
            # GROUPED against the narrow cache (g = kv head, r = query
            # head within the group) so the full-width K/V transient is
            # never materialized — reading the cache narrow is the
            # bandwidth saving the smaller cache exists for.
            if cfg.kv_heads != cfg.n_heads:
                rep = cfg.n_heads // cfg.kv_heads
                qg = q.reshape(b, t, cfg.kv_heads, rep, cfg.head_dim)
                s = jnp.einsum("bqgrd,bkgd->bgrqk", qg, ck,
                               preferred_element_type=jnp.float32) * scale
                s = jnp.where(mask[None, None, None], s, NEG_INF)
                p = jax.nn.softmax(s, axis=-1)
                attn = jnp.einsum("bgrqk,bkgd->bqgrd", p.astype(dt), cv,
                                  preferred_element_type=jnp.float32)
                attn = attn.reshape(b, t, cfg.n_heads, cfg.head_dim)
            else:
                s = jnp.einsum("bqhd,bkhd->bhqk", q, ck,
                               preferred_element_type=jnp.float32) * scale
                s = jnp.where(mask[None, None], s, NEG_INF)
                p = jax.nn.softmax(s, axis=-1)
                attn = jnp.einsum("bhqk,bkhd->bqhd", p.astype(dt), cv,
                                  preferred_element_type=jnp.float32)
            x = x + attn.astype(dt).reshape(b, t, -1) @ layer["wo"].astype(dt)
            x = constrain(x, spmd.AXIS_DATA, None, None)

            h = _rmsnorm(x, layer["ln2"])
            if "moe" in layer:
                from kubegpu_tpu.workload.moe import moe_ffn

                ffn_out, _ = moe_ffn(layer["moe"], h, dt,
                                     top_k=cfg.moe_top_k)
                x = x + ffn_out
            else:
                up = h @ layer["w_up"].astype(dt)
                gate = jax.nn.silu(h @ layer["w_gate"].astype(dt))
                x = x + (up * gate) @ layer["w_down"].astype(dt)
            x = constrain(x, spmd.AXIS_DATA, None, None)

        x = _rmsnorm(x, params["final_norm"])
        logits = x @ params["unembed"].astype(dt)
        return logits.astype(jnp.float32), new_cache

    return step


def make_generate(cfg: TransformerConfig, mesh=None,
                  max_seq: int | None = None):
    """Build ``generate(params, prompt, n_new) -> tokens [B, n_new]``:
    greedy decoding as prefill + ONE `lax.scan` over decode steps, all
    inside a single jit. ``n_new`` is static (it sizes the scan)."""
    max_seq = max_seq or cfg.max_seq
    step = make_forward_step(cfg, mesh)

    def generate(params, prompt, n_new: int):
        b, t0 = prompt.shape
        # Size the cache to THIS call's horizon, not max_seq: prompt and
        # n_new are static at trace time, so the cache (and with it
        # every decode step's full-cache attention read — the HBM
        # traffic that bounds decode on TPU) shrinks to the 128-aligned
        # generation length. Masked positions contributed exactly zero,
        # so tokens are unchanged; a longer horizon in a later call just
        # traces a new program (same as any new static n_new).
        horizon = min(max_seq, -(-(t0 + n_new) // 128) * 128)
        cache = init_cache(cfg, b, horizon)
        logits, cache = step(params, cache, prompt, 0)
        first = jnp.argmax(logits[:, -1, :], axis=-1)

        def body(carry, _):
            cache, token, pos = carry
            logits, cache = step(params, cache, token[:, None], pos)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1)
            return (cache, nxt, pos + 1), token

        (_, last, _), toks = lax.scan(
            body, (cache, first, jnp.int32(t0)), None, length=n_new - 1)
        # toks: [n_new-1, B] of the fed-in tokens; append the final one
        out = jnp.concatenate(
            [jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1) \
            if n_new > 1 else last[:, None]
        return out

    return generate

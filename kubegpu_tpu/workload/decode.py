"""Autoregressive inference: KV-cache prefill + decode, sharded.

The serving half of the workload layer (training lives in `train.py`).
TPU-first design:

- **Static shapes**: the KV cache is allocated at ``max_seq`` up front and
  written with `lax.dynamic_update_slice`; attention always reads the full
  cache with a position mask, so every decode step compiles to the same
  program (no shape-driven recompiles).
- **Token loop inside jit**: `make_generate` runs the whole greedy decode
  as one `lax.scan`, not a Python loop — one compilation, no host↔device
  round-trip per token.
- **Sharding**: batch on ``data``, heads on ``model`` (the cache is
  sharded the same way); decode chunks are tiny so the ``seq`` axis is
  unused here — GSPMD inserts the same per-layer collectives as training.

Matches `model.make_forward` logits exactly (same weights, same RoPE
positions) — asserted by test.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from kubegpu_tpu.workload import spmd
from kubegpu_tpu.workload.model import (TransformerConfig, _rmsnorm, _rope)

NEG_INF = -1e30


def init_cache(cfg: TransformerConfig, batch: int, max_seq: int):
    """Zeroed per-layer KV cache: list of {"k","v"} of
    ``[B, max_seq, KV_H, D]`` in the compute dtype. With GQA
    (``cfg.n_kv_heads``) the cache is n_heads/kv_heads smaller — the
    decode-bandwidth saving the variant exists for."""
    dt = cfg.compute_dtype()
    shape = (batch, max_seq, cfg.kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            for _ in range(cfg.n_layers)]


def cache_pspecs(cfg: TransformerConfig, mesh=None):
    """PartitionSpec pytree matching `init_cache`: batch on data, KV
    heads on model when they divide the model-axis size (mirrors the kv
    weight sharding); a narrow GQA/MQA cache whose kv_heads the mesh
    cannot split is replicated on that axis instead of crashing."""
    from jax.sharding import PartitionSpec as P

    head_axis = spmd.AXIS_MODEL
    if mesh is not None:
        tp = mesh.shape.get(spmd.AXIS_MODEL, 1)
        if tp > 1 and cfg.kv_heads % tp:
            head_axis = None
    spec = P(spmd.AXIS_DATA, None, head_axis, None)
    return [{"k": spec, "v": spec} for _ in range(cfg.n_layers)]


def make_forward_step(cfg: TransformerConfig, mesh=None):
    """Build ``step(params, cache, tokens, start_pos) ->
    (logits, new_cache)``: process a chunk of ``tokens [B, T]`` whose
    first token sits at absolute position ``start_pos``, attending over
    everything cached so far plus the chunk itself. Used with T=prompt
    length for prefill and T=1 for decode.

    ``start_pos`` may be a scalar (whole batch at one depth — the
    `make_generate` path) or a ``[B]`` vector of PER-ROW positions —
    what continuous batching needs, where each slot sits at its own
    generation depth (`kubegpu_tpu.workload.serve`)."""

    def constrain(x, *spec):
        if mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))

    def step(params, cache, tokens, start_pos):
        dt = cfg.compute_dtype()
        b, t = tokens.shape
        s_max = cache[0]["k"].shape[1]
        scale = cfg.head_dim ** -0.5
        start_pos = jnp.asarray(start_pos)
        per_row = start_pos.ndim == 1
        row_start = jnp.broadcast_to(start_pos, (b,))  # [B] either way
        x = params["embed"].astype(dt)[tokens]
        x = constrain(x, spmd.AXIS_DATA, None, None)
        positions = row_start[:, None] + jnp.arange(t)[None, :]
        # chunk position i attends cache positions <= row_start + i
        # (and, with a sliding window, only the newest window of them)
        kv_pos = jnp.arange(s_max)
        q_pos = row_start[:, None, None] + jnp.arange(t)[None, :, None]
        mask = kv_pos[None, None, :] <= q_pos          # [B, T, S]
        if cfg.attn_window:
            mask &= kv_pos[None, None, :] > q_pos - cfg.attn_window

        def update_cache(buf, new):
            """Write the [B, T, ...] chunk at each row's own offset."""
            if not per_row:
                return lax.dynamic_update_slice(
                    buf, new, (0, start_pos, 0, 0))
            return jax.vmap(
                lambda row_buf, row_new, p: lax.dynamic_update_slice(
                    row_buf, row_new, (p, 0, 0)))(buf, new, row_start)

        new_cache = []
        for layer, kv in zip(params["layers"], cache):
            h = _rmsnorm(x, layer["ln1"])
            q = (h @ layer["wq"].astype(dt)).reshape(b, t, cfg.n_heads,
                                                     cfg.head_dim)
            k = (h @ layer["wk"].astype(dt)).reshape(b, t, cfg.kv_heads,
                                                     cfg.head_dim)
            v = (h @ layer["wv"].astype(dt)).reshape(b, t, cfg.kv_heads,
                                                     cfg.head_dim)
            q = _rope(q, positions, cfg.rope_theta)
            k = _rope(k, positions, cfg.rope_theta)
            ck = update_cache(kv["k"], k.astype(dt))
            cv = update_cache(kv["v"], v.astype(dt))
            new_cache.append({"k": ck, "v": cv})

            # bf16 operands, f32 accumulation — MXU-native (see
            # model._causal_attention). With GQA the query heads are
            # GROUPED against the narrow cache (g = kv head, r = query
            # head within the group) so the full-width K/V transient is
            # never materialized — reading the cache narrow is the
            # bandwidth saving the smaller cache exists for.
            if cfg.kv_heads != cfg.n_heads:
                rep = cfg.n_heads // cfg.kv_heads
                qg = q.reshape(b, t, cfg.kv_heads, rep, cfg.head_dim)
                s = jnp.einsum("bqgrd,bkgd->bgrqk", qg, ck,
                               preferred_element_type=jnp.float32) * scale
                s = jnp.where(mask[:, None, None], s, NEG_INF)
                p = jax.nn.softmax(s, axis=-1)
                attn = jnp.einsum("bgrqk,bkgd->bqgrd", p.astype(dt), cv,
                                  preferred_element_type=jnp.float32)
                attn = attn.reshape(b, t, cfg.n_heads, cfg.head_dim)
            else:
                s = jnp.einsum("bqhd,bkhd->bhqk", q, ck,
                               preferred_element_type=jnp.float32) * scale
                s = jnp.where(mask[:, None], s, NEG_INF)
                p = jax.nn.softmax(s, axis=-1)
                attn = jnp.einsum("bhqk,bkhd->bqhd", p.astype(dt), cv,
                                  preferred_element_type=jnp.float32)
            x = x + attn.astype(dt).reshape(b, t, -1) @ layer["wo"].astype(dt)
            x = constrain(x, spmd.AXIS_DATA, None, None)

            h = _rmsnorm(x, layer["ln2"])
            if "moe" in layer:
                from kubegpu_tpu.workload.moe import moe_ffn

                ffn_out, _ = moe_ffn(layer["moe"], h, dt,
                                     top_k=cfg.moe_top_k)
                x = x + ffn_out
            else:
                up = h @ layer["w_up"].astype(dt)
                gate = jax.nn.silu(h @ layer["w_gate"].astype(dt))
                x = x + (up * gate) @ layer["w_down"].astype(dt)
            x = constrain(x, spmd.AXIS_DATA, None, None)

        x = _rmsnorm(x, params["final_norm"])
        logits = x @ params["unembed"].astype(dt)
        return logits.astype(jnp.float32), new_cache

    return step


def validate_sampling(cfg: TransformerConfig, temperature: float,
                      top_k: int, top_p: float) -> int:
    """Shared validation + clamp for every decode entry point
    (`make_generate`, `serve.DecodeServer`): raises on out-of-range
    values, rejects truncation flags under greedy (they would be
    silently ignored), and returns ``top_k`` clamped to the vocab
    (k >= vocab keeps every token — same distribution — so clamping
    beats an obscure lax.top_k shape error at trace time)."""
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if temperature == 0.0 and (top_k or top_p < 1.0):
        raise ValueError(
            "top_k/top_p truncate SAMPLING and are ignored by greedy "
            "decode — set temperature > 0 to use them")
    return min(top_k, cfg.vocab)


def _truncate_logits(z, top_k: int, top_p: float):
    """Apply top-k / nucleus truncation to scaled logits ``z [B, V]``
    (masked tokens go to NEG_INF). Shared by direct sampling and
    speculative decoding so both see the IDENTICAL truncated support."""
    if top_k:
        kth = lax.top_k(z, top_k)[0][:, -1:]  # k-th largest per row
        z = jnp.where(z < kth, NEG_INF, z)
    if top_p < 1.0:
        z_sorted = jnp.sort(z, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(z_sorted, axis=-1)
        # exclusive cumulative mass BEFORE each token: a token is kept
        # while the mass of strictly-better tokens is < top_p, so the
        # boundary token that crosses top_p is included (standard
        # nucleus semantics) and the top-1 token can never be dropped.
        cum = jnp.cumsum(probs, axis=-1) - probs
        keep = cum < top_p
        # threshold = smallest kept logit; mask everything below it
        thr = jnp.min(jnp.where(keep, z_sorted, jnp.inf),
                      axis=-1, keepdims=True)
        z = jnp.where(z < thr, NEG_INF, z)
    return z


def truncated_probs(logits, temperature: float, top_k: int, top_p: float):
    """The exact distribution `_select_token` samples from:
    temperature-scaled softmax truncated to the top-k/nucleus support
    and RENORMALIZED, per row. Speculative sampling runs its acceptance
    rule on these for BOTH target and draft — the standard
    truncate-and-renormalize construction under which the
    rejection-resampling theorem stays exact for the truncated target."""
    z = _truncate_logits(logits.astype(jnp.float32) / temperature,
                         top_k, top_p)
    return jax.nn.softmax(z, axis=-1)


def _select_token_rows(logits, keys, temperature: float, top_k: int,
                       top_p: float):
    """Per-row-keyed variant of `_select_token`: row ``i`` of
    ``logits [B, V]`` samples with its OWN key ``keys[i]``. The serving
    layer keys every selection by (request, position) so the sampled
    stream of a request is a pure function of its rng lineage — the same
    tokens whatever batch it shares, whichever step or fused chunk emits
    them, and whether admission happened early or late. Shares
    `_truncate_logits` with `_select_token`, so both see the identical
    truncated support; greedy ignores the keys entirely."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    z = _truncate_logits(logits.astype(jnp.float32) / temperature,
                         top_k, top_p)
    return jax.vmap(lambda k, row: jax.random.categorical(k, row))(keys, z)


def make_decode_chunk(cfg: TransformerConfig, mesh=None, chunk: int = 16,
                      temperature: float = 0.0, top_k: int = 0,
                      top_p: float = 1.0, eos_id: int | None = None):
    """Build the FUSED DECODE CHUNK the serving loop dispatches: one
    `lax.scan` generating up to ``chunk`` tokens for every batch row in
    a single device program, so the host pays one dispatch (and one
    readback) per chunk instead of one per token.

    ``chunk_step(params, cache, tok, pos, active, budget, skeys) ->
    (new_cache, toks [B, chunk], n_emit [B], tok, pos, active)``:

    - ``tok``/``pos`` are each row's last emitted token and its absolute
      position (the forward-step invariant `serve.DecodeServer` keeps);
    - ``active [B] bool`` masks rows that should emit; inactive rows
      ride along FROZEN: their ``tok``/``pos`` stop advancing and each
      iteration rewrites the same K/V position with the same values —
      idempotent, and overwritten by prefill when the slot is reused;
    - ``budget [B] int32`` is each row's remaining ``max_new`` quota;
    - ``skeys [B, 2] uint32`` are per-row sampling key roots: the
      selection at position ``p`` uses ``fold_in(skeys[b], p)``
      (`_select_token_rows`), making sampled tokens position-keyed and
      therefore identical between this fused path and the per-token
      oracle path.

    EOS (when ``eos_id`` is set) and budget exhaustion are detected ON
    DEVICE: a row that emits EOS or its budget-th token freezes for the
    rest of the chunk, so each row's emissions are a clean prefix of
    ``toks[b]`` of length ``n_emit[b]`` — everything the host needs
    comes back in ONE batched transfer."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    step = make_forward_step(cfg, mesh)
    top_k = validate_sampling(cfg, temperature, top_k, top_p)
    sampling = temperature != 0.0

    def chunk_step(params, cache, tok, pos, active, budget, skeys):
        def body(carry, _):
            cache, tok, pos, active, emitted = carry
            logits, cache = step(params, cache, tok[:, None], pos)
            if sampling:
                rkeys = jax.vmap(jax.random.fold_in)(skeys, pos)
                nxt = _select_token_rows(logits[:, -1, :], rkeys,
                                         temperature, top_k, top_p)
            else:
                nxt = jnp.argmax(logits[:, -1, :], axis=-1)
            nxt = nxt.astype(jnp.int32)
            emit = active
            nxt = jnp.where(emit, nxt, tok)       # frozen rows hold
            pos = jnp.where(emit, pos + 1, pos)
            emitted = emitted + emit.astype(jnp.int32)
            alive = emitted < budget
            if eos_id is not None:
                alive &= nxt != eos_id            # EOS is emitted, THEN
            active = active & alive               # the row freezes
            return (cache, nxt, pos, active, emitted), \
                jnp.where(emit, nxt, 0)

        carry0 = (cache, tok, pos, active, jnp.zeros_like(pos))
        (cache, tok, pos, active, emitted), toks = lax.scan(
            body, carry0, None, length=chunk)
        return (cache, jnp.swapaxes(toks, 0, 1), emitted, tok, pos,
                active)

    return chunk_step


def _select_token(logits, key, temperature: float, top_k: int,
                  top_p: float):
    """Pick the next token per batch row from ``logits [B, V]``.

    ``temperature == 0`` is greedy argmax (no key needed). Otherwise
    temperature-scaled sampling, optionally truncated to the ``top_k``
    highest-logit tokens and/or the ``top_p`` nucleus (smallest set of
    tokens whose probability mass reaches ``top_p``). Truncations are
    implemented as logit thresholds so everything stays static-shaped
    for the decode scan."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    z = _truncate_logits(logits.astype(jnp.float32) / temperature,
                         top_k, top_p)
    return jax.random.categorical(key, z, axis=-1)


def make_generate(cfg: TransformerConfig, mesh=None,
                  max_seq: int | None = None, temperature: float = 0.0,
                  top_k: int = 0, top_p: float = 1.0):
    """Build ``generate(params, prompt, n_new[, rng]) -> tokens
    [B, n_new]``: decoding as prefill + ONE `lax.scan` over decode
    steps, all inside a single jit. ``n_new`` is static (it sizes the
    scan). Sampling is configured here (static by construction):
    ``temperature=0`` (default) is greedy; >0 samples, truncated by
    ``top_k``/``top_p``, and ``generate`` then requires ``rng``."""
    max_seq = max_seq or cfg.max_seq
    step = make_forward_step(cfg, mesh)
    sampling = temperature != 0.0
    top_k = validate_sampling(cfg, temperature, top_k, top_p)

    def generate(params, prompt, n_new: int, rng=None):
        if sampling and rng is None:
            raise ValueError("sampling decode needs an rng key")
        if rng is None:
            rng = jax.random.PRNGKey(0)  # unused by greedy selection
        b, t0 = prompt.shape
        if t0 + n_new > max_seq:
            # beyond max_seq, dynamic_update_slice would CLAMP every
            # later write to the last cache slot while RoPE positions
            # keep advancing — silently corrupt output, so refuse
            raise ValueError(
                f"prompt ({t0}) + n_new ({n_new}) exceeds max_seq "
                f"({max_seq}); raise max_seq= on make_generate")
        # Size the cache to THIS call's horizon, not max_seq: prompt and
        # n_new are static at trace time, so the cache (and with it
        # every decode step's full-cache attention read — the HBM
        # traffic that bounds decode on TPU) shrinks to the 128-aligned
        # generation length. Masked positions contributed exactly zero,
        # so tokens are unchanged; a longer horizon in a later call just
        # traces a new program (same as any new static n_new).
        horizon = min(max_seq, -(-(t0 + n_new) // 128) * 128)
        cache = init_cache(cfg, b, horizon)
        logits, cache = step(params, cache, prompt, 0)
        first = _select_token(logits[:, -1, :], jax.random.fold_in(rng, 0),
                              temperature, top_k, top_p)

        def body(carry, i):
            cache, token, pos = carry
            logits, cache = step(params, cache, token[:, None], pos)
            nxt = _select_token(logits[:, -1, :],
                                jax.random.fold_in(rng, i),
                                temperature, top_k, top_p)
            return (cache, nxt, pos + 1), token

        (_, last, _), toks = lax.scan(
            body, (cache, first, jnp.int32(t0)),
            jnp.arange(1, n_new), length=n_new - 1)
        # toks: [n_new-1, B] of the fed-in tokens; append the final one
        out = jnp.concatenate(
            [jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1) \
            if n_new > 1 else last[:, None]
        return out

    return generate

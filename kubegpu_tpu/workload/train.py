"""Sharded training step: the "8-chip JAX job" end of the contract.

`make_train_step` jits the full step (fwd + bwd + optimizer) over a mesh
with explicit in/out shardings, so XLA GSPMD inserts exactly the
collectives the layout implies: psum over ``model`` for tensor-parallel
matmuls, ppermute ring over ``seq`` inside attention, gradient all-reduce
over ``data``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax import lax

from kubegpu_tpu.workload import spmd
from kubegpu_tpu.workload.model import TransformerConfig, init_params, make_loss_fn


def default_optimizer(lr: float = 3e-4):
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.01)


def init_sharded(rng, cfg: TransformerConfig, mesh, optimizer=None,
                 init_optimizer: bool = True):
    """Initialize params (+ optimizer state) already laid out on the mesh.

    ``init_optimizer=False`` returns ``opt_state=None`` without ever
    materializing the O(model) moment tensors — LoRA fine-tuning keeps
    only adapter-sized optimizer state, so allocating (then discarding)
    full-model Adam moments would defeat the point and can OOM exactly
    the large-model case adapters exist to fit."""
    optimizer = optimizer or default_optimizer()
    specs = spmd.param_pspecs(cfg)
    from jax.sharding import NamedSharding, PartitionSpec

    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, PartitionSpec))
    # traced-shapes: rng [2] uint32; one-shot setup trace, never retraced
    init = jax.jit(partial(init_params, cfg=cfg), out_shardings=shardings)
    params = init(rng)
    if not init_optimizer:
        return params, None, optimizer
    # traced-shapes: params pytree, fixed by cfg; one-shot setup trace
    opt_state = jax.jit(optimizer.init)(params)
    # moment leaves inherit the params' NamedShardings, but scalar state
    # (Adam's count) falls out of jit committed to device 0 — replicate
    # it over the mesh so EVERY leaf's committed sharding is
    # mesh-consistent (shard-aware checkpoint restore and the donated
    # train step both rely on a single device set)
    rep = NamedSharding(mesh, PartitionSpec())
    opt_state = jax.tree.map(
        lambda x: x if isinstance(getattr(x, "sharding", None),
                                  NamedSharding)
        else jax.device_put(x, rep), opt_state)
    return params, opt_state, optimizer


def make_train_step(cfg: TransformerConfig, mesh, optimizer=None,
                    accum_steps: int = 1):
    """Jitted ``step(params, opt_state, tokens) -> (params, opt_state, loss)``.

    ``accum_steps`` > 1 = gradient accumulation: the batch is split into
    that many equal microbatches, gradients are averaged over a
    `lax.scan` of fwd+bwd passes, and ONE optimizer update applies —
    the standard trade of step latency for effective batch sizes whose
    activations exceed HBM. For dense configs equal microbatch sizes
    make the averaged loss/grads exactly the full-batch mean (the loss
    is token-mean). MoE configs are the usual approximation: the
    load-balancing aux loss is nonlinear in the batch, so the averaged
    per-microbatch aux differs slightly from the full-batch value —
    the standard behavior of accumulated MoE training."""
    optimizer = optimizer or default_optimizer()
    loss_fn = make_loss_fn(cfg, mesh)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def step(params, opt_state, tokens):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        else:
            b = tokens.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps {accum_steps}")
            micro = tokens.reshape(accum_steps, b // accum_steps,
                                   *tokens.shape[1:])

            def acc(carry, mb):
                loss_sum, grads_sum = carry
                loss, grads = jax.value_and_grad(loss_fn)(params, mb)
                return (loss_sum + loss,
                        jax.tree.map(jnp.add, grads_sum, grads)), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (loss, grads), _ = lax.scan(
                acc, (jnp.zeros((), jnp.float32), zeros), micro)
            loss = loss / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        # donate exactly as the mesh path below: params and opt_state
        # are threaded through every call and the caller drops the old
        # references on rebind, so XLA may update both in place instead
        # of paying a full HBM copy per step
        # traced-shapes: params/opt_state pytrees fixed by cfg; tokens
        # [B, S] int32, fixed per training run
        return jax.jit(step, donate_argnums=(0, 1))
    from jax.sharding import NamedSharding, PartitionSpec

    pspecs = spmd.param_pspecs(cfg)
    p_shard = jax.tree.map(
        lambda s: NamedSharding(mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    batch_shard = NamedSharding(mesh, spmd.batch_pspec())
    # traced-shapes: params/opt_state pytrees fixed by cfg; tokens
    # [B, S] int32, fixed per training run
    return jax.jit(
        step,
        in_shardings=(p_shard, None, batch_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )


def train_step_model_flops(cfg: TransformerConfig, batch: int,
                           seq: int) -> int:
    """Analytic model FLOPs for one train step (fwd + bwd = 3x the
    forward matmul FLOPs) — the numerator of every MFU/TF-per-second
    number this repo reports, kept in ONE place so the bench headline
    (bench.py) and the preset tuner (tools/tune_preset.py) can never
    rank candidates by divergent formulas:

      linear layers: 6 * tokens * (L*(4*d^2 + 3*d*d_ff) + d*vocab)
      attention, causal: fwd 4*B*T^2*d*L * 0.5 -> fwd+bwd 6*B*T^2*d*L
    """
    d, L, dff, V = cfg.d_model, cfg.n_layers, cfg.d_ff, cfg.vocab
    flops_linear = 6 * batch * seq * (L * (4 * d * d + 3 * d * dff) + d * V)
    flops_attn = 6 * batch * seq * seq * d * L
    return flops_linear + flops_attn

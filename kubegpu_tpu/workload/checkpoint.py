"""Training-state checkpointing.

Uses Orbax when importable (the standard JAX checkpointing stack, async-
and shard-aware); otherwise a plain numpy fallback with identical call
semantics, so the train loop never changes. The scheduler side needs no
file checkpoints at all — the API server's annotations are its checkpoint
(SURVEY.md §6) — this is for the *workload*, which the reference does not
have.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _flatten(tree: Any) -> Tuple[List[Any], Any]:
    leaves, treedef = jax.tree.flatten(tree)
    return leaves, treedef


def save_checkpoint(path: str, state: Any, step: int) -> str:
    """Write ``state`` (any pytree) at ``path``; returns the final path."""
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        return _save_numpy(path, state, step)
    ckpt = ocp.StandardCheckpointer()
    full = os.path.abspath(os.path.join(path, f"step_{step}"))
    # Hand the jax.Array pytree to orbax directly: it saves shard-aware
    # (multi-host safe) without gathering to one host's memory.
    ckpt.save(full, state, force=True)
    ckpt.wait_until_finished()
    return full


def _save_numpy(path: str, state: Any, step: int) -> str:
    """Atomic: write into a temp dir, then rename — a pod SIGKILLed
    mid-save must never leave a half-written ``step_N`` that the
    replacement pod picks as latest and dies on (crash loop)."""
    full = os.path.join(path, f"step_{step}")
    tmp = f"{full}.tmp-{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    leaves, treedef = _flatten(state)
    np.savez(os.path.join(tmp, "leaves.npz"),
             **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump({"step": step, "n_leaves": len(leaves)}, f)
    if os.path.isdir(full):
        import shutil

        shutil.rmtree(full)
    os.rename(tmp, full)
    return full


def restore_checkpoint(path: str, like: Any) -> Tuple[Any, int]:
    """Restore the NEWEST readable ``step_*`` under ``path`` into the
    structure of ``like``; returns (state, step) or (None, -1) when
    absent. A corrupt/partial newest step (crashed writer, torn copy)
    falls back to the next-older one instead of crash-looping the
    replacement pod."""
    if not os.path.isdir(path):
        return None, -1
    steps = sorted(
        (int(d.split("_", 1)[1]), d) for d in os.listdir(path)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit())
    for step, dirname in reversed(steps):
        full = os.path.join(path, dirname)
        try:
            npz = os.path.join(full, "leaves.npz")
            if os.path.exists(npz):
                data = np.load(npz)
                leaves, treedef = _flatten(like)
                if len(data.files) != len(leaves):
                    raise ValueError(
                        f"{full}: {len(data.files)} saved leaves vs "
                        f"{len(leaves)} expected — different model config")
                restored = []
                for i, want in enumerate(leaves):
                    got = data[f"leaf_{i}"]
                    if np.shape(got) != jnp.shape(want):
                        # e.g. a pre-GQA checkpoint against a GQA config:
                        # fail HERE with the leaf named, not deep inside
                        # a jitted train step
                        raise ValueError(
                            f"{full}: leaf {i} shape {np.shape(got)} != "
                            f"expected {jnp.shape(want)} — checkpoint "
                            "from a different model config")
                    restored.append(jnp.asarray(got))
                return jax.tree.unflatten(treedef, restored), step

            import orbax.checkpoint as ocp

            ckpt = ocp.StandardCheckpointer()

            def abstract(x: Any) -> jax.ShapeDtypeStruct:
                # carry the live shardings so orbax restores each leaf
                # straight onto the mesh layout `like` uses (without
                # this it falls back to the saved-topology layout, which
                # is wrong on a different mesh); sharding=None is the
                # constructor's accepted default
                dt = getattr(x, "dtype", None)
                if dt is None:
                    dt = jnp.asarray(x).dtype
                return jax.ShapeDtypeStruct(
                    jnp.shape(x), dt,
                    sharding=getattr(x, "sharding", None))

            return ckpt.restore(full, jax.tree.map(abstract, like)), step
        except Exception:
            # unreadable step: fall back to the next-older one — but
            # loudly, or a systematic failure (e.g. a mesh mismatch that
            # fails EVERY step) would masquerade as "no checkpoint" and
            # silently retrain from step 0
            logging.getLogger(__name__).warning(
                "checkpoint %s unreadable, trying older", full,
                exc_info=True)
            continue
    return None, -1

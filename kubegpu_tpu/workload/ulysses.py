"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

The second canonical long-context strategy next to `ring.py` (DeepSpeed-
Ulysses pattern, public recipe): instead of rotating K/V blocks around the
sequence axis, one `all_to_all` converts the sequence-sharded layout
``[B, T/sp, H, D]`` into a head-sharded layout ``[B, T, H/sp, D]``, local
attention runs over the FULL sequence for the shard's head subset (so the
Pallas flash kernel applies unchanged), and a second all_to_all restores
sequence sharding.

Trade-off vs the ring: two all-to-alls of activation size (bisection-
bandwidth bound, still ICI when the scheduler hands out a contiguous
sub-mesh) instead of ``sp`` neighbor ppermutes of K/V size, and no
per-step softmax merging — better for large head counts / short-ish
sequences, while the ring wins when T is huge and K/V blocks are small.
The framework offers both; `model.py` picks via config.

Requires the local head count to divide by the sequence-axis size.
"""

from __future__ import annotations

import jax
from jax import lax


def _scatter_heads(x, axis_name: str):
    """[B, T_local, H, D] -> [B, T_global, H/sp, D]: split the head dim
    across the axis, gather the sequence dim. Shard order along the axis
    matches global block order, so concatenation restores the true
    sequence."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _gather_heads(x, axis_name: str):
    """Inverse: [B, T_global, H/sp, D] -> [B, T_local, H, D]."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, axis_name: str, scale: float,
                      use_flash: bool = False, interpret: bool = False,
                      window: int = 0):
    """Exact causal attention over the ``axis_name``-sharded sequence.

    q, k, v: per-shard blocks ``[B, T_local, H, D]`` (already RoPE'd with
    global positions). Returns ``[B, T_local, H, D]``. Matches single-
    shard causal attention bit-for-bit up to float tolerance. ``window``
    passes straight to the full-sequence local attend (positions are
    global after the all-to-all).
    """
    sp = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % sp != 0:
        raise ValueError(
            f"ulysses sequence parallelism needs heads%sp==0, got "
            f"{h} local heads over sp={sp}; use ring attention instead")
    qg = _scatter_heads(q, axis_name)
    kg = _scatter_heads(k, axis_name)
    vg = _scatter_heads(v, axis_name)
    if use_flash:
        from kubegpu_tpu.workload.kernels.flash import flash_attention

        out = flash_attention(qg, kg, vg, scale, interpret=interpret,
                              window=window)
    else:
        # the single-shard fused attention is the ONE implementation both
        # seq_impl strategies must match; lazy import avoids a cycle
        # (model imports this module lazily too)
        from kubegpu_tpu.workload.model import _causal_attention

        out = _causal_attention(qg, kg, vg, scale, window=window)
    return _gather_heads(out, axis_name)


def make_sharded_ulysses_attention(mesh, data_axis: str, seq_axis: str,
                                   model_axis: str, scale: float,
                                   use_flash: bool = False,
                                   interpret: bool = False,
                                   window: int = 0):
    """shard_map wrapper mirroring `ring.make_sharded_ring_attention`:
    same in/out specs, so `model.py` can swap strategies freely."""
    from jax.sharding import PartitionSpec as P

    spec = P(data_axis, seq_axis, model_axis, None)

    def fn(q, k, v):
        return ulysses_attention(q, k, v, seq_axis, scale,
                                 use_flash=use_flash, interpret=interpret,
                                 window=window)

    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)

"""Continuous-batching decode server: slot-based serving, TPU-static.

The serving core the decode layer was missing: requests with different
prompt lengths and arrival times share one decode batch. The design is
the TPU-idiomatic slot variant of continuous batching (vLLM-style
iteration scheduling, without paging): a fixed number of ``slots``, each
owning one row of a static KV cache, so EVERY device program is compiled
once —

- **admit**: a free slot prefills the request's prompt through a
  padded-to-bucket forward (one compile per bucket size, not per prompt
  length). Padded positions write garbage K/V beyond the true length,
  which is safe: decode overwrites position ``p`` exactly when the token
  at ``p`` is generated, and a query at position ``q`` only attends
  ``kv <= q`` — every attended entry has been overwritten by a real
  write first.
- **fused chunk** (the default data plane): ONE jitted `lax.scan`
  (`decode.make_decode_chunk`) generates up to ``chunk`` tokens for all
  slots per dispatch, detecting per-slot EOS/``max_new`` ON DEVICE and
  freezing finished rows behind an active mask, so the host pays one
  dispatch and ONE batched readback per chunk instead of per token.
  Continuous batching happens at chunk boundaries: ``step()`` drains
  finished slots, admits queued requests through the bucketed prefill,
  then launches the next chunk — idle slots ride along masked, shapes
  stay static, everything compiles once.
- **finish**: on EOS or the request's ``max_new``, the slot returns to
  the free list and the next queued request is admitted — requests never
  wait for a whole batch to drain, which is the point.

``KGTPU_FUSED_SERVE=0`` disables the fused chunk and runs the original
per-token host loop — one jitted forward per generated token — which
survives as the differential ORACLE (mirroring ``KGTPU_VECTORIZE`` /
``KGTPU_BATCH``): tests/test_serve_fused.py pins token-for-token float32
parity between the two paths, greedy and sampled.

Sampling keys are position-keyed per request: the selection at absolute
position ``p`` of request ``rid`` uses ``fold_in(fold_in(rng, rid), p)``
(`decode._select_token_rows`). A request's sampled stream is therefore a
pure function of its rng lineage — independent of which slot it lands
in, which other requests share the batch, when it was admitted, and
whether the fused chunk or the per-token oracle emitted it. That is
what makes cross-path sampled parity testable at all.

Numerics: per-request tokens match `make_generate` exactly in float32
(asserted by tests/test_serve.py). On TPU in bfloat16 the padded-bucket
prefill rounds differently than the exact-length prefill (MXU results
are shape-dependent), so near-tie argmaxes can flip — measured ~7e-3
max logit difference on a v5e, the same class of divergence as the
flash-vs-XLA attention A/B, and immaterial for trained models whose
token margins dwarf rounding.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubegpu_tpu import metrics
from kubegpu_tpu.workload.decode import (_select_token, _select_token_rows,
                                         init_cache, make_decode_chunk,
                                         make_forward_step, truncated_probs,
                                         validate_sampling)
from kubegpu_tpu.workload.model import TransformerConfig


@dataclass
class _Request:
    rid: int
    prompt: list
    max_new: int
    out: list = field(default_factory=list)
    done: bool = False
    t_submit: float = 0.0


def _bucket_for(n: int, buckets: tuple) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket "
                     f"{buckets[-1]}")


class DecodeServer:
    """Slot-based continuous-batching decode engine.

    ``submit()`` enqueues a request; ``run()`` (or repeated ``step()``)
    drives admission + decoding until done. Greedy by default; sampling
    via ``temperature``/``top_k``/``top_p`` + ``rng`` like
    `make_generate`.

    The data plane is the FUSED DECODE CHUNK: each ``step()`` admits
    what fits, then dispatches one jitted scan that emits up to
    ``chunk`` tokens per slot with on-device EOS/``max_new`` freezing
    and one batched readback (``KGTPU_FUSED_SERVE=0`` falls back to the
    per-token oracle loop).

    ``prefix_cache_size > 0`` enables PREFIX REUSE: the K/V of served
    prompts is retained (LRU, that many entries) and a request whose
    prompt extends a stored one splices the cached rows in and prefills
    only the remainder — the static-shape answer to paged serving's
    prefix cache, exact because the shared prefix's K/V is
    position-identical. ``prefix_hits``/``prefix_misses`` count reuse.

    With ``draft_params``/``draft_cfg`` the server decodes
    SPECULATIVELY per slot: each round proposes ``lookahead`` draft
    tokens for every slot, verifies all slots in one batched target
    forward, and emits each slot's accepted prefix plus one token —
    greedy-exact, and distribution-exact under sampling (both target
    and draft rows truncated-and-renormalized, `speculative.py`'s
    acceptance rule vmapped over slots). On the fused path the whole
    round — draft scan, target verify, accept/resample, commit and
    freezing — is ONE jitted program, and ``spec_rounds`` consecutive
    rounds ride in a single dispatch with one batched readback.
    ``spec_accepted``/``spec_proposed`` track the live acceptance rate.
    """

    def __init__(self, cfg: TransformerConfig, params, slots: int = 4,
                 max_seq: int | None = None, mesh=None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, eos_id: int | None = None,
                 prefill_buckets: tuple = (32, 128, 512), rng=None,
                 draft_params=None, draft_cfg: TransformerConfig | None = None,
                 lookahead: int = 4, prefix_cache_size: int = 0,
                 chunk: int = 16, spec_rounds: int = 4):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if spec_rounds < 1:
            raise ValueError(f"spec_rounds must be >= 1, got {spec_rounds}")
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError("draft_params and draft_cfg go together")
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_seq = max_seq or cfg.max_seq
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = int(validate_sampling(cfg, self.temperature, top_k,
                                           top_p))
        self.top_p = float(top_p)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if hasattr(rng, "dtype") and jnp.issubdtype(rng.dtype,
                                                    jax.dtypes.prng_key):
            rng = jax.random.key_data(rng)  # raw [2] uint32 throughout
        self.rng = rng
        self.chunk = int(chunk)
        self.fused = os.environ.get("KGTPU_FUSED_SERVE", "1") != "0"
        # max_seq is always the terminal bucket: any prompt that fits the
        # cache must be admissible, just at the coarsest padding
        self.buckets = tuple(sorted(
            {b for b in prefill_buckets if b < self.max_seq}
            | {self.max_seq}))
        self._fstep = make_forward_step(cfg, mesh)
        self.cache = init_cache(cfg, slots, self.max_seq)
        self.pos = np.zeros(slots, np.int32)        # next position per slot
        self.tok = np.zeros(slots, np.int32)        # last emitted token
        # per-slot sampling key root = fold_in(rng, rid) of the resident
        # request; zeros while idle (greedy never reads them)
        self.slot_key = np.zeros((slots, 2), np.uint32)
        self.slot_req: list = [None] * slots        # _Request or None
        self._free = list(range(slots))
        self._queue: list = []
        self._requests: dict = {}
        self._next_rid = 0

        def prefill(params, cache, tokens, slot, true_len, rkey):
            """Pad-to-bucket prompt pass for ONE slot; returns the updated
            big cache and the slot's sampled first token. Selection runs
            inside the trace so admission pays ONE scalar readback, not a
            vocab-row transfer + eager select per request. ``rkey`` is
            the request's key root; the first selection happens at
            position ``true_len - 1``, so its key is the same
            position-keyed fold the decode paths use."""
            small = init_cache(cfg, 1, tokens.shape[1])
            logits, small = self._fstep(params, small, tokens, 0)
            new_cache = []
            for big, sm in zip(cache, small):
                new_cache.append({
                    k: jax.lax.dynamic_update_slice(
                        big[k], sm[k], (slot, 0, 0, 0)) for k in ("k", "v")})
            last = jax.lax.dynamic_index_in_dim(
                logits[0], true_len - 1, axis=0, keepdims=False)
            key = jax.random.fold_in(rkey, true_len - 1)
            first = _select_token(last[None, :], key, self.temperature,
                                  self.top_k, self.top_p)[0]
            return new_cache, first.astype(jnp.int32)

        # donate the cache: it is threaded through every call and the old
        # reference is dropped on reassignment, so XLA updates it in
        # place instead of copying the whole multi-slot cache per token
        # traced-shapes: tokens [1, bucket] int32 — varies per prefill
        # bucket (one trace per bucket by design); slot/true_len scalar
        # int32, rkey [2] uint32
        self._prefill = jax.jit(prefill, donate_argnums=(1,))

        # -- prefix reuse: stored K/V of previously-served prompts lets a
        # request sharing a prefix skip recomputing it (the static-shape
        # answer to paged serving's prefix cache). Entries are keyed by
        # the EXACT token prefix; a hit splices the stored rows into the
        # slot and prefills only the remainder. Bucket-padding garbage in
        # stored entries is safe by the same overwrite-before-attend
        # argument as the admit prefill.
        from collections import OrderedDict

        if prefix_cache_size < 0:
            raise ValueError(
                f"prefix_cache_size must be >= 0, got {prefix_cache_size}")
        self.prefix_cache_size = int(prefix_cache_size)
        self._prefix_cache: OrderedDict = OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0

        def rem_prefill(params, cache, stored, rem_tokens, slot, plen,
                        rem_true, rkey):
            """Splice a stored prefix (``[1, b, ...]`` per layer) into a
            fresh row, run the remainder chunk at position ``plen``, and
            write the row back into the big cache at ``slot``; returns
            the cache and the sampled first token (device-side selection,
            as in ``prefill``). The selection position is the FULL
            prompt's last token, ``plen + rem_true - 1``, so a prefix
            hit samples the identical first token as a full prefill."""
            s_max = cache[0]["k"].shape[1]
            row = []
            for big, st in zip(cache, stored):
                row.append({
                    k: jax.lax.dynamic_update_slice(
                        jnp.zeros((1, s_max) + big[k].shape[2:],
                                  big[k].dtype), st[k], (0, 0, 0, 0))
                    for k in ("k", "v")})
            logits, row = self._fstep(params, row, rem_tokens, plen)
            new_cache = []
            for big, rw in zip(cache, row):
                new_cache.append({
                    k: jax.lax.dynamic_update_slice(
                        big[k], rw[k], (slot, 0, 0, 0)) for k in ("k", "v")})
            last = jax.lax.dynamic_index_in_dim(
                logits[0], rem_true - 1, axis=0, keepdims=False)
            key = jax.random.fold_in(rkey, plen + rem_true - 1)
            first = _select_token(last[None, :], key, self.temperature,
                                  self.top_k, self.top_p)[0]
            return new_cache, first.astype(jnp.int32)

        # traced-shapes: rem_tokens [1, bucket] int32 — varies per
        # remainder bucket; stored pytree [1, plen_bucket] per layer —
        # varies per stored-prefix bucket; scalars int32, rkey [2] uint32
        self._rem_prefill = jax.jit(rem_prefill, donate_argnums=(1,))

        def snapshot_prefix(cache, slot, b: int):
            """Copy one slot's first ``b`` cache positions out for the
            prefix store. Runs eagerly: admission is host-paced anyway,
            and eager keeps ``b`` free to vary per bucket without a
            stale-trace hazard."""
            return [
                {k: jax.lax.dynamic_slice(
                    big[k], (slot, 0, 0, 0),
                    (1, b) + big[k].shape[2:]) for k in ("k", "v")}
                for big in cache]

        self._snapshot_prefix = snapshot_prefix

        def decode(params, cache, tok, pos, skeys):
            logits, cache = self._fstep(params, cache, tok[:, None], pos)
            if self.temperature != 0.0:
                rkeys = jax.vmap(jax.random.fold_in)(skeys, pos)
            else:
                rkeys = skeys  # greedy: keys unread
            nxt = _select_token_rows(logits[:, -1, :], rkeys,
                                     self.temperature, self.top_k,
                                     self.top_p)
            return cache, nxt.astype(jnp.int32)

        # traced-shapes: tok/pos [S] int32, skeys [S, 2] uint32 — fixed
        # per server (S = slots), one trace for the server's lifetime
        self._decode = jax.jit(decode, donate_argnums=(1,))

        # -- fused decode chunk: the default serving data plane. One
        # dispatch emits up to `chunk` tokens per slot with on-device
        # EOS/budget freezing (decode.make_decode_chunk has the chunk
        # semantics; the kill switch is read once at construction).
        chunk_step = make_decode_chunk(
            cfg, mesh, chunk=self.chunk, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p, eos_id=eos_id)
        # traced-shapes: tok/pos/budget [S] int32, active [S] bool,
        # skeys [S, 2] uint32 — fixed per server, one trace for the
        # server's lifetime (chunk length is static by construction)
        self._chunk_step = jax.jit(chunk_step, donate_argnums=(1,))

        # -- speculative mode: a draft model proposes k tokens per slot,
        # the target verifies every slot's chunk in ONE batched forward
        self.spec = draft_params is not None
        self.spec_rounds = int(spec_rounds)
        self.spec_accepted = 0   # drafts the target accepted
        self.spec_proposed = 0   # drafts proposed (k per active slot)
        if self.spec:
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError("draft and target must share a vocabulary")
            if lookahead < 1 or lookahead + 2 > min(self.buckets):
                # idle slots ride along writing garbage K/V at positions
                # 0..k; the admit prefill overwrites [0, bucket), so the
                # smallest bucket bounds the lookahead
                raise ValueError(
                    f"lookahead must be in [1, {min(self.buckets) - 2}] "
                    f"(smallest prefill bucket {min(self.buckets)})")
            self.k = lookahead
            self.draft_params = draft_params
            self._dstep = make_forward_step(draft_cfg, mesh)
            self.dcache = init_cache(draft_cfg, slots, self.max_seq)
            self.prev = np.zeros(slots, np.int32)   # token at pos-1
            sampling = self.temperature != 0.0
            k = self.k

            def round_keys(skeys, pos):
                """Per-slot key root for ONE speculative round: keyed by
                (request, round-start position) so both serve paths —
                per-round host loop and fused multi-round scan — derive
                the identical randomness for the identical round."""
                return jax.vmap(jax.random.fold_in)(skeys, pos)

            def pick_rows(logits, rkeys):
                """[S, V] + per-row keys -> next token per slot (and the
                truncated distribution row each was sampled from)."""
                if sampling:
                    p = truncated_probs(logits, self.temperature,
                                        self.top_k, self.top_p)
                    toks = jax.vmap(
                        lambda kk, row: jax.random.categorical(
                            kk, jnp.log(jnp.maximum(row, 1e-30))))(rkeys, p)
                    return toks, p
                return jnp.argmax(logits, axis=-1), jnp.zeros(())

            def spec_propose(dparams, dcache, prev, tok, pos, skeys):
                """k draft tokens per slot. First step reprocesses
                [prev, tok] at pos-1: after a fully-accepted round the
                draft never saw its own k-th proposal (K/V hole at
                pos-1); re-writing prev there fills it, idempotently
                otherwise — same catch-up trick as
                speculative.draft_propose, batched. Draft step ``i``
                samples with ``fold_in(round_key, i)`` per slot."""
                rkeys = round_keys(skeys, pos)

                def fold_i(i):
                    return jax.vmap(
                        lambda kk: jax.random.fold_in(kk, i))(rkeys)

                chunk = jnp.stack([prev, tok], axis=1)         # [S, 2]
                start = jnp.maximum(pos - 1, 0)
                logits, dcache = self._dstep(dparams, dcache, chunk, start)
                first, q0 = pick_rows(logits[:, -1, :], fold_i(0))

                def body(carry, i):
                    dcache, t, p = carry
                    logits, dcache = self._dstep(dparams, dcache,
                                                 t[:, None], p)
                    nxt, q = pick_rows(logits[:, -1, :], fold_i(i))
                    return (dcache, nxt, p + 1), (nxt, q)

                (dcache, _, _), (toks, qs) = lax.scan(
                    body, (dcache, first, pos + 1), jnp.arange(1, k))
                drafts = first[:, None] if k == 1 else jnp.concatenate(
                    [first[:, None], jnp.swapaxes(toks, 0, 1)], axis=1)
                if sampling:
                    q_rows = q0[:, None] if k == 1 else jnp.concatenate(
                        [q0[:, None], jnp.moveaxis(qs, 0, 1)], axis=1)
                else:
                    q_rows = jnp.zeros(())
                return dcache, drafts.astype(jnp.int32), q_rows

            def spec_verify(params, cache, chunk, pos, skeys, q_rows):
                """One batched target forward over every slot's
                [last, d1..dk] chunk; per-slot acceptance. The accept /
                resample key is ``fold_in(round_key, k)`` per slot —
                disjoint from the draft-step indices 0..k-1. Greedy
                ignores ``q_rows`` (pass a dummy scalar)."""
                logits, cache = self._fstep(params, cache, chunk, pos)
                s = chunk.shape[0]
                if sampling:
                    from kubegpu_tpu.workload.speculative import \
                        accept_resample

                    rkeys = round_keys(skeys, pos)
                    akeys = jax.vmap(
                        lambda kk: jax.random.fold_in(kk, k))(rkeys)
                    p_rows = truncated_probs(
                        logits.reshape(s * (k + 1), -1), self.temperature,
                        self.top_k, self.top_p).reshape(s, k + 1, -1)
                    n_acc, extra = jax.vmap(accept_resample)(
                        p_rows, q_rows, chunk[:, 1:], akeys)
                    return cache, n_acc, extra
                greedy = jnp.argmax(logits, axis=-1)       # [S, k+1]
                agree = chunk[:, 1:] == greedy[:, :-1]
                n_acc = jnp.argmin(jnp.concatenate(
                    [agree, jnp.zeros((s, 1), bool)],
                    axis=1).astype(jnp.int32), axis=1)
                extra = jnp.take_along_axis(
                    greedy, n_acc[:, None], axis=1)[:, 0]
                return cache, n_acc, extra

            def spec_commit(chunk2, n_acc, extra, prev, tok, pos, active,
                            budget):
                """The round's emission + freezing, ON DEVICE: the
                emitted tokens are ``drafts[:n_acc] + [extra]``,
                truncated at the first EOS or the budget, exactly the
                host commit loop's semantics. Returns the masked
                candidate row [S, k+1] (valid prefix of length n_emit),
                per-slot n_emit, and the advanced carry state —
                continuing rows advance ``n_acc + 1`` positions with the
                standard catch-up anchor; finished/frozen rows hold."""
                s = chunk2.shape[0]
                idx = jnp.arange(k + 1)[None, :]
                drafts_pad = jnp.concatenate(
                    [chunk2[:, 1:], jnp.zeros((s, 1), jnp.int32)], axis=1)
                cand = jnp.where(idx == n_acc[:, None],
                                 extra[:, None].astype(jnp.int32),
                                 drafts_pad)
                emit = (idx <= n_acc[:, None]) & (idx < budget[:, None]) \
                    & active[:, None]
                if self.eos_id is not None:
                    is_eos = cand == self.eos_id
                    before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
                        - is_eos.astype(jnp.int32)
                    emit &= before == 0        # EOS is emitted, THEN
                    hit_eos = jnp.any(emit & is_eos, axis=1)
                else:
                    hit_eos = jnp.zeros(s, bool)
                n_emit = jnp.sum(emit.astype(jnp.int32), axis=1)
                fin = hit_eos | (n_emit >= budget)
                cont = active & ~fin
                anchor = jnp.take_along_axis(
                    chunk2, n_acc[:, None], axis=1)[:, 0]
                prev = jnp.where(cont, anchor, prev)
                tok = jnp.where(cont, extra.astype(jnp.int32), tok)
                pos = jnp.where(cont, pos + n_acc + 1, pos)
                budget = budget - n_emit
                return (jnp.where(emit, cand, 0), n_emit, prev, tok, pos,
                        cont, budget)

            # oracle-path jits (KGTPU_FUSED_SERVE=0): one dispatch per
            # propose and one per verify, host-side commit per round
            # traced-shapes: prev/tok/pos [S] int32, skeys [S, 2] uint32
            # — fixed per server, one trace for the server's lifetime
            self._spec_propose = jax.jit(spec_propose, donate_argnums=(1,))
            # traced-shapes: chunk [S, k+1] int32, pos [S] int32, skeys
            # [S, 2] uint32, q_rows [S, k, V] f32 (or scalar when
            # greedy) — fixed per server
            self._spec_verify = jax.jit(spec_verify, donate_argnums=(1,))
            self._spec_commit = spec_commit  # host path commits eagerly

            R = self.spec_rounds

            def spec_fused(params, dparams, cache, dcache, prev, tok, pos,
                           active, budget, skeys):
                """``spec_rounds`` speculative rounds in ONE dispatch:
                each scan iteration is draft-propose -> target-verify ->
                accept/resample -> commit, all on device. Every round's
                emissions land contiguously in a per-slot output buffer
                (each round writes its full k+1 candidate row at the
                slot's running offset and advances the offset by that
                round's n_emit, so later rounds overwrite the invalid
                tail and the valid tokens stay a clean prefix). Finished
                slots freeze and ride the remaining rounds masked."""
                buf0 = jnp.zeros((slots, R * (k + 1)), jnp.int32)
                off0 = jnp.zeros(slots, jnp.int32)
                acc0 = jnp.zeros(slots, jnp.int32)

                def round_body(carry, _):
                    (cache, dcache, prev, tok, pos, active, budget, off,
                     buf, acc_n, acc_d) = carry
                    was_active = active
                    dcache, drafts, q_rows = spec_propose(
                        dparams, dcache, prev, tok, pos, skeys)
                    chunk2 = jnp.concatenate([tok[:, None], drafts],
                                             axis=1)
                    cache, n_acc, extra = spec_verify(
                        params, cache, chunk2, pos, skeys, q_rows)
                    (cand, n_emit, prev, tok, pos, active,
                     budget) = spec_commit(chunk2, n_acc, extra, prev,
                                           tok, pos, active, budget)
                    buf = jax.vmap(
                        lambda row, c, o: lax.dynamic_update_slice(
                            row, c, (o,)))(buf, cand, off)
                    off = off + n_emit
                    acc_n = acc_n + jnp.where(was_active, n_acc, 0)
                    acc_d = acc_d + jnp.where(was_active, k, 0)
                    return (cache, dcache, prev, tok, pos, active,
                            budget, off, buf, acc_n, acc_d), None

                (cache, dcache, prev, tok, pos, active, _, off, buf,
                 acc_n, acc_d), _ = lax.scan(
                    round_body,
                    (cache, dcache, prev, tok, pos, active, budget, off0,
                     buf0, acc0, acc0), None, length=R)
                return (cache, dcache, buf, off, prev, tok, pos, active,
                        acc_n, acc_d)

            # traced-shapes: prev/tok/pos/budget [S] int32, active [S]
            # bool, skeys [S, 2] uint32 — fixed per server, one trace
            # for the server's lifetime (k and spec_rounds are static)
            # donate the caches AND the [S] carry vectors (prev/tok/
            # pos/active): all thread in and out every dispatch, and
            # the host uploads fresh buffers each step anyway
            self._spec_fused = jax.jit(
                spec_fused, donate_argnums=(2, 3, 4, 5, 6, 7))

            def dprefill(dparams, dcache, tokens, slot):
                small = init_cache(draft_cfg, 1, tokens.shape[1])
                _, small = self._dstep(dparams, small, tokens, 0)
                new_cache = []
                for big, sm in zip(dcache, small):
                    new_cache.append({
                        kk: jax.lax.dynamic_update_slice(
                            big[kk], sm[kk], (slot, 0, 0, 0))
                        for kk in ("k", "v")})
                return new_cache

            # traced-shapes: tokens [1, bucket] int32 — varies per
            # prefill bucket (one trace per bucket by design)
            self._dprefill = jax.jit(dprefill, donate_argnums=(1,))

    # -- public API ----------------------------------------------------------

    def submit(self, prompt, max_new: int) -> int:
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        # speculative verify may write k+1 positions past the last
        # emitted token before truncation — reserve the headroom
        headroom = (self.k + 1) if self.spec else 0
        if len(prompt) + max_new + headroom > self.max_seq:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new}"
                + (f" + lookahead headroom {headroom}" if headroom else "")
                + f" exceeds max_seq {self.max_seq}")
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, list(prompt), max_new,
                       t_submit=time.perf_counter())
        self._requests[rid] = req
        self._queue.append(req)
        metrics.SERVE_QUEUE_DEPTH.set(len(self._queue))
        return rid

    def result(self, rid: int) -> list | None:
        """Tokens of a finished request (None while in flight). Reading a
        finished result EVICTS it — a long-running server must not retain
        every request it ever served; re-reading a consumed rid raises."""
        req = self._requests.get(rid)
        if req is None:
            raise KeyError(
                f"unknown request id {rid} (never submitted, or its "
                "result was already read)")
        if not req.done:
            return None
        del self._requests[rid]
        return list(req.out)

    @property
    def pending(self) -> int:
        return len(self._queue) + sum(r is not None for r in self.slot_req)

    @property
    def spec_acceptance(self) -> float:
        """Live draft-acceptance rate (accepted / proposed)."""
        return self.spec_accepted / max(1, self.spec_proposed)

    def step(self) -> int:
        """Admit what fits, then decode for every active slot: one fused
        chunk (up to ``chunk`` tokens per slot — or ``spec_rounds``
        speculative rounds — per dispatch), or a single token on the
        per-token oracle path (``KGTPU_FUSED_SERVE=0``). Returns the
        number of active slots stepped."""
        while self._free and self._queue:
            self._admit(self._free.pop(0), self._queue.pop(0))
        metrics.SERVE_QUEUE_DEPTH.set(len(self._queue))
        active = [s for s in range(self.slots)
                  if self.slot_req[s] is not None]
        metrics.SERVE_SLOT_UTILIZATION.set(len(active) / self.slots)
        if not active:
            return 0
        if self.spec:
            return self._spec_fused_step(active) if self.fused \
                else self._spec_step(active)
        if self.fused:
            return self._fused_step(active)
        t0 = time.perf_counter()
        # ONE upload per step: tok and pos ride a single [2, S] transfer
        # and are sliced apart device-side (two jnp.asarray calls were
        # two host->device dispatches per token)
        tp = jnp.asarray(np.stack([self.tok, self.pos]))
        self.cache, nxt = self._decode(self.params, self.cache, tp[0],
                                       tp[1], jnp.asarray(self.slot_key))
        # host-sync: allowed -- the per-step token readback is the
        # product on the oracle path: EOS tests and per-request output
        # append are host decisions (ONE batched [S] transfer per step)
        nxt = np.asarray(nxt)
        itl_ms = (time.perf_counter() - t0) * 1e3
        for s in active:
            req = self.slot_req[s]
            tok = int(nxt[s])
            req.out.append(tok)
            self.tok[s] = tok
            self.pos[s] += 1
            metrics.SERVE_ITL_MS.observe(itl_ms)
            if (self.eos_id is not None and tok == self.eos_id) or \
                    len(req.out) >= req.max_new:
                self._finish(s)
        return len(active)

    def _budget_mask(self, active: list):
        """Per-slot remaining ``max_new`` quota + active mask for the
        fused programs (idle slots: zero budget, masked off)."""
        budget = np.zeros(self.slots, np.int32)
        amask = np.zeros(self.slots, bool)
        for s in active:
            budget[s] = self.slot_req[s].max_new - len(self.slot_req[s].out)
            amask[s] = True
        return budget, amask

    def _fused_step(self, active: list) -> int:
        """One fused decode chunk for the whole batch: up to ``chunk``
        tokens per slot in one dispatch, EOS/budget freezing on device,
        ONE batched readback at the chunk boundary."""
        t0 = time.perf_counter()
        budget, amask = self._budget_mask(active)
        # ONE upload per chunk: tok/pos/budget ride a single [3, S]
        # transfer and are sliced apart device-side
        up = jnp.asarray(np.stack([self.tok, self.pos, budget]))
        self.cache, toks, n_emit, tok_n, pos_n, _ = self._chunk_step(
            self.params, self.cache, up[0], up[1], jnp.asarray(amask),
            up[2], jnp.asarray(self.slot_key))
        # host-sync: allowed -- ONE batched readback per CHUNK (the
        # fused data plane's whole point): every slot's emitted prefix,
        # count, and carry state ride a single transfer; EOS/max_new
        # were already decided on device
        toks, n_emit, tok_n, pos_n = jax.device_get(
            (toks, n_emit, tok_n, pos_n))
        wall_ms = (time.perf_counter() - t0) * 1e3
        for s in active:
            req = self.slot_req[s]
            new = [int(x) for x in toks[s, :int(n_emit[s])]]
            req.out.extend(new)
            self.tok[s] = int(tok_n[s])
            self.pos[s] = int(pos_n[s])
            if new:
                metrics.SERVE_ITL_MS.observe(wall_ms / len(new))
            if (self.eos_id is not None and new
                    and new[-1] == self.eos_id) or \
                    len(req.out) >= req.max_new:
                self._finish(s)
        return len(active)

    def _spec_fused_step(self, active: list) -> int:
        """``spec_rounds`` fused speculative rounds in one dispatch:
        draft scans, batched verifies, acceptance and commit all on
        device; ONE batched readback returns every slot's contiguous
        emissions plus the advanced carry state."""
        t0 = time.perf_counter()
        budget, amask = self._budget_mask(active)
        up = jnp.asarray(np.stack([self.prev, self.tok, self.pos, budget]))
        (self.cache, self.dcache, buf, n_tot, prev_n, tok_n, pos_n,
         act_n, acc_n, acc_d) = self._spec_fused(
            self.params, self.draft_params, self.cache, self.dcache,
            up[0], up[1], up[2], jnp.asarray(amask), up[3],
            jnp.asarray(self.slot_key))
        # host-sync: allowed -- ONE batched readback per fused dispatch
        # covering spec_rounds speculative rounds: emissions, counts,
        # carry state and acceptance tallies in a single transfer
        got = jax.device_get(
            (buf, n_tot, prev_n, tok_n, pos_n, act_n, acc_n, acc_d))
        buf, n_tot, prev_n, tok_n, pos_n, act_n, acc_n, acc_d = got
        wall_ms = (time.perf_counter() - t0) * 1e3
        self.spec_accepted += int(acc_n.sum())
        self.spec_proposed += int(acc_d.sum())
        for s in active:
            req = self.slot_req[s]
            new = [int(x) for x in buf[s, :int(n_tot[s])]]
            req.out.extend(new)
            if new:
                metrics.SERVE_ITL_MS.observe(wall_ms / len(new))
            if not bool(act_n[s]):
                self._finish(s)
            else:
                self.prev[s] = int(prev_n[s])
                self.tok[s] = int(tok_n[s])
                self.pos[s] = int(pos_n[s])
        return len(active)

    def _spec_step(self, active: list) -> int:
        """One speculative round for the whole batch on the ORACLE path:
        k draft proposals per slot, one batched target verify, per-slot
        acceptance, commit on host."""
        t0 = time.perf_counter()
        # ONE upload per round: prev/tok/pos ride a single [3, S]
        # transfer and are sliced apart device-side (the previous four
        # jnp.asarray calls were four host->device dispatches per round)
        htp = jnp.asarray(np.stack([self.prev, self.tok, self.pos]))
        skeys = jnp.asarray(self.slot_key)
        self.dcache, drafts, q_rows = self._spec_propose(
            self.draft_params, self.dcache, htp[0], htp[1], htp[2], skeys)
        chunk = jnp.concatenate([htp[1][:, None], drafts], axis=1)
        self.cache, n_acc, extra = self._spec_verify(
            self.params, self.cache, chunk, htp[2], skeys, q_rows)
        # host-sync: allowed -- one batched transfer per round (remote
        # rigs pay RTT per fetch; three sequential gets tripled the
        # round's latency floor)
        n_acc, extra, chunk_np = jax.device_get((n_acc, extra, chunk))
        wall_ms = (time.perf_counter() - t0) * 1e3
        for s in active:
            req = self.slot_req[s]
            n = int(n_acc[s])
            self.spec_accepted += n
            self.spec_proposed += self.k
            # the round's tokens: n accepted drafts + correction/bonus
            new = [int(x) for x in chunk_np[s, 1:n + 1]] + [int(extra[s])]
            emitted = []
            for t in new:
                emitted.append(t)
                if (self.eos_id is not None and t == self.eos_id) or \
                        len(req.out) + len(emitted) >= req.max_new:
                    break
            req.out.extend(emitted)
            metrics.SERVE_ITL_MS.observe(wall_ms / len(emitted))
            if (self.eos_id is not None and self.eos_id in emitted) or \
                    len(req.out) >= req.max_new:
                self._finish(s)
            else:
                # full round emitted: advance exactly n+1 positions
                self.pos[s] += n + 1
                self.prev[s] = int(chunk_np[s, n])
                self.tok[s] = emitted[-1]
        return len(active)

    def run(self, max_steps: int = 10_000) -> None:
        """Drive until every submitted request finishes."""
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(f"not drained after {max_steps} steps")

    # -- internals -----------------------------------------------------------

    def _prefix_lookup(self, prompt: list):
        """Longest stored entry that is a PROPER prefix of ``prompt``
        (LRU-refreshed), or None."""
        best = None
        for key in self._prefix_cache:
            if len(key) < len(prompt) and \
                    (best is None or len(key) > len(best)) and \
                    tuple(prompt[:len(key)]) == key:
                best = key
        if best is None:
            return None
        self._prefix_cache.move_to_end(best)
        return best, self._prefix_cache[best]

    def _prefix_store(self, prompt: list, slot: int) -> None:
        """Store the full prompt's K/V AND its bucket-aligned prefixes:
        the dominant serving pattern is a shared system prompt with
        different user suffixes, and those only ever match an
        INTERMEDIATE prefix — a cache holding only full prompts would
        never hit it."""
        keys = [(tuple(prompt[:b]), b)
                for b in self.buckets if b < len(prompt)]
        keys.append((tuple(prompt), _bucket_for(len(prompt), self.buckets)))
        for key, b in keys:
            if key in self._prefix_cache:
                self._prefix_cache.move_to_end(key)
                continue
            self._prefix_cache[key] = self._snapshot_prefix(
                self.cache, jnp.int32(slot), b)
        while len(self._prefix_cache) > self.prefix_cache_size:
            self._prefix_cache.popitem(last=False)

    def _admit(self, slot: int, req: _Request) -> None:
        n = len(req.prompt)
        bucket = _bucket_for(n, self.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = req.prompt
        hit = self._prefix_lookup(req.prompt) if self.prefix_cache_size \
            else None
        if hit is not None:
            pkey, stored = hit
            plen = len(pkey)
            rem = req.prompt[plen:]
            rb = _bucket_for(len(rem), self.buckets)
            if plen + rb > self.max_seq:
                # the padded remainder would write past the cache end,
                # where dynamic_update_slice CLAMPS the start and
                # silently corrupts the prefix K/V (the hazard
                # decode.make_generate refuses up front) — full prefill
                # instead of a corrupting shortcut
                hit = None
        # the request's key root: every selection of this request, on
        # every path, folds its position into this key
        req_key = jax.random.fold_in(self.rng, req.rid)
        if hit is not None:
            rem_padded = np.zeros((1, rb), np.int32)
            rem_padded[0, :len(rem)] = rem
            self.cache, first_t = self._rem_prefill(
                self.params, self.cache, stored, jnp.asarray(rem_padded),
                jnp.int32(slot), jnp.int32(plen), jnp.int32(len(rem)),
                req_key)
            self.prefix_hits += 1
        else:
            self.cache, first_t = self._prefill(
                self.params, self.cache, jnp.asarray(padded),
                jnp.int32(slot), jnp.int32(n), req_key)
            if self.prefix_cache_size:
                self.prefix_misses += 1
        if self.prefix_cache_size:
            self._prefix_store(req.prompt, slot)
        # host-sync: allowed -- admission readback: ONE scalar per
        # admitted request (selection already ran inside the prefill
        # trace); the host must see the token for EOS + output append
        first = int(first_t)
        metrics.SERVE_TTFT_MS.observe(
            (time.perf_counter() - req.t_submit) * 1e3)
        req.out.append(first)
        self.slot_req[slot] = req
        self.tok[slot] = first
        self.pos[slot] = n
        # host-sync: allowed -- one [2] uint32 key mirror per ADMITTED
        # request (not per token): the host keeps it to re-upload with
        # every fused dispatch so selection keys survive slot recycling
        self.slot_key[slot] = np.asarray(req_key, np.uint32)
        if self.spec:
            self.dcache = self._dprefill(
                self.draft_params, self.dcache, jnp.asarray(padded),
                jnp.int32(slot))
            self.prev[slot] = req.prompt[-1]  # draft catch-up anchor
        if (self.eos_id is not None and first == self.eos_id) or \
                len(req.out) >= req.max_new:
            self._finish(slot)

    def _finish(self, slot: int) -> None:
        req = self.slot_req[slot]
        req.done = True
        self.slot_req[slot] = None
        self.pos[slot] = 0
        self.tok[slot] = 0
        self.slot_key[slot] = 0
        if self.spec:
            self.prev[slot] = 0
        self._free.append(slot)

"""Continuous-batching decode server: slot-based serving, TPU-static.

The serving core the decode layer was missing: requests with different
prompt lengths and arrival times share one decode batch. The design is
the TPU-idiomatic slot variant of continuous batching (vLLM-style
iteration scheduling, without paging): a fixed number of ``slots``, each
owning one row of a static KV cache, so EVERY device program is compiled
once —

- **admit**: a free slot prefills the request's prompt through a
  padded-to-bucket forward (one compile per bucket size, not per prompt
  length). Padded positions write garbage K/V beyond the true length,
  which is safe: decode overwrites position ``p`` exactly when the token
  at ``p`` is generated, and a query at position ``q`` only attends
  ``kv <= q`` — every attended entry has been overwritten by a real
  write first.
- **step**: ONE jitted forward for all slots at per-row positions
  (`make_forward_step`'s vector ``start_pos``), sampling or greedy via
  `_select_token`. Idle slots ride along at position 0 with a dummy
  token (static shapes beat masking them out; their cache writes land in
  a slot that prefill fully overwrites on reuse).
- **finish**: on EOS or the request's ``max_new``, the slot returns to
  the free list and the next queued request is admitted — requests never
  wait for a whole batch to drain, which is the point.

Numerics: per-request tokens match `make_generate` exactly in float32
(asserted by tests/test_serve.py). On TPU in bfloat16 the padded-bucket
prefill rounds differently than the exact-length prefill (MXU results
are shape-dependent), so near-tie argmaxes can flip — measured ~7e-3
max logit difference on a v5e, the same class of divergence as the
flash-vs-XLA attention A/B, and immaterial for trained models whose
token margins dwarf rounding.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubegpu_tpu.workload.decode import (_select_token, init_cache,
                                         make_forward_step,
                                         validate_sampling)
from kubegpu_tpu.workload.model import TransformerConfig


@dataclass
class _Request:
    rid: int
    prompt: list
    max_new: int
    out: list = field(default_factory=list)
    done: bool = False


def _bucket_for(n: int, buckets: tuple) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket "
                     f"{buckets[-1]}")


class DecodeServer:
    """Slot-based continuous-batching decode engine.

    ``submit()`` enqueues a request; ``run()`` (or repeated ``step()``)
    drives admission + decoding until done. Greedy by default; sampling
    via ``temperature``/``top_k``/``top_p`` + ``rng`` like
    `make_generate`.
    """

    def __init__(self, cfg: TransformerConfig, params, slots: int = 4,
                 max_seq: int | None = None, mesh=None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, eos_id: int | None = None,
                 prefill_buckets: tuple = (32, 128, 512), rng=None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_seq = max_seq or cfg.max_seq
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = int(validate_sampling(cfg, self.temperature, top_k,
                                           top_p))
        self.top_p = float(top_p)
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        # max_seq is always the terminal bucket: any prompt that fits the
        # cache must be admissible, just at the coarsest padding
        self.buckets = tuple(sorted(
            {b for b in prefill_buckets if b < self.max_seq}
            | {self.max_seq}))
        self._fstep = make_forward_step(cfg, mesh)
        self.cache = init_cache(cfg, slots, self.max_seq)
        self.pos = np.zeros(slots, np.int32)        # next position per slot
        self.tok = np.zeros(slots, np.int32)        # last emitted token
        self.slot_req: list = [None] * slots        # _Request or None
        self._free = list(range(slots))
        self._queue: list = []
        self._requests: dict = {}
        self._next_rid = 0
        self._tick = 0

        def prefill(params, cache, tokens, slot, true_len):
            """Pad-to-bucket prompt pass for ONE slot; returns the updated
            big cache and the logits row at the prompt's true end."""
            small = init_cache(cfg, 1, tokens.shape[1])
            logits, small = self._fstep(params, small, tokens, 0)
            new_cache = []
            for big, sm in zip(cache, small):
                new_cache.append({
                    k: jax.lax.dynamic_update_slice(
                        big[k], sm[k], (slot, 0, 0, 0)) for k in ("k", "v")})
            last = jax.lax.dynamic_index_in_dim(
                logits[0], true_len - 1, axis=0, keepdims=False)
            return new_cache, last

        # donate the cache: it is threaded through every call and the old
        # reference is dropped on reassignment, so XLA updates it in
        # place instead of copying the whole multi-slot cache per token
        self._prefill = jax.jit(prefill, donate_argnums=(1,))

        def decode(params, cache, tok, pos, key):
            logits, cache = self._fstep(params, cache, tok[:, None], pos)
            nxt = _select_token(logits[:, -1, :], key, self.temperature,
                                self.top_k, self.top_p)
            return cache, nxt.astype(jnp.int32)

        self._decode = jax.jit(decode, donate_argnums=(1,))

    # -- public API ----------------------------------------------------------

    def submit(self, prompt, max_new: int) -> int:
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if len(prompt) + max_new > self.max_seq:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} exceeds "
                f"max_seq {self.max_seq}")
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, list(prompt), max_new)
        self._requests[rid] = req
        self._queue.append(req)
        return rid

    def result(self, rid: int) -> list | None:
        """Tokens of a finished request (None while in flight). Reading a
        finished result EVICTS it — a long-running server must not retain
        every request it ever served; re-reading a consumed rid raises."""
        req = self._requests.get(rid)
        if req is None:
            raise KeyError(
                f"unknown request id {rid} (never submitted, or its "
                "result was already read)")
        if not req.done:
            return None
        del self._requests[rid]
        return list(req.out)

    @property
    def pending(self) -> int:
        return len(self._queue) + sum(r is not None for r in self.slot_req)

    def step(self) -> int:
        """Admit what fits, decode one token for every active slot.
        Returns the number of active slots stepped."""
        while self._free and self._queue:
            self._admit(self._free.pop(0), self._queue.pop(0))
        active = [s for s in range(self.slots)
                  if self.slot_req[s] is not None]
        if not active:
            return 0
        key = jax.random.fold_in(self.rng, self._tick)
        self._tick += 1
        self.cache, nxt = self._decode(
            self.params, self.cache, jnp.asarray(self.tok),
            jnp.asarray(self.pos), key)
        nxt = np.asarray(nxt)
        for s in active:
            req = self.slot_req[s]
            tok = int(nxt[s])
            req.out.append(tok)
            self.tok[s] = tok
            self.pos[s] += 1
            if (self.eos_id is not None and tok == self.eos_id) or \
                    len(req.out) >= req.max_new:
                self._finish(s)
        return len(active)

    def run(self, max_steps: int = 10_000) -> None:
        """Drive until every submitted request finishes."""
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(f"not drained after {max_steps} steps")

    # -- internals -----------------------------------------------------------

    def _admit(self, slot: int, req: _Request) -> None:
        n = len(req.prompt)
        bucket = _bucket_for(n, self.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = req.prompt
        self.cache, last = self._prefill(
            self.params, self.cache, jnp.asarray(padded),
            jnp.int32(slot), jnp.int32(n))
        key = jax.random.fold_in(self.rng, self._tick)
        self._tick += 1
        first = int(np.asarray(_select_token(
            last[None, :], key, self.temperature, self.top_k, self.top_p))[0])
        req.out.append(first)
        self.slot_req[slot] = req
        self.tok[slot] = first
        self.pos[slot] = n
        if (self.eos_id is not None and first == self.eos_id) or \
                len(req.out) >= req.max_new:
            self._finish(slot)

    def _finish(self, slot: int) -> None:
        req = self.slot_req[slot]
        req.done = True
        self.slot_req[slot] = None
        self.pos[slot] = 0
        self.tok[slot] = 0
        self._free.append(slot)

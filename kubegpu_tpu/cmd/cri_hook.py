"""``kgtpu-cri-hook``: per-container device injection, OCI-hook style.

Reference: `crishim/pkg/kubecri/docker_container.go` — the shim intercepts
CreateContainer and rewrites the container config. The modern equivalent
plugs into containerd as an NRI/OCI hook: the runtime pipes the container
config JSON to stdin and uses the rewritten JSON from stdout.

Preferred mode: thin client against the node agent's PERSISTENT rewrite
endpoint (``--server http://127.0.0.1:PORT`` or ``unix:///run/kgtpu.sock``)
— discovery ran once in the agent, and the interception path is a running
server like the reference's (`docker_container.go:115-191`). Without
``--server`` it falls back to standalone mode (own discovery pass per
invocation) so the hook still works when no agent is running.

    kgtpu-cri-hook --server unix:///run/kgtpu.sock \\
        --pod mypod --container main < config.json
"""

from __future__ import annotations

import argparse
import json
import sys

from kubegpu_tpu.cmd import common


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--server", default=None,
                        help="node agent CRI endpoint (http://... or "
                             "unix:///...); omit for standalone mode")
    parser.add_argument("--api", default="http://127.0.0.1:8070")
    parser.add_argument("--pod", required=True)
    parser.add_argument("--container", required=True)
    parser.add_argument("--backend", default="native",
                        choices=["native", "fake-v5p", "fake-single"])
    parser.add_argument("--sysfs-root", default="/sys/class")
    parser.add_argument("--config", default=None)
    args = parser.parse_args(argv)
    common.merge_flags(args, common.load_config(args.config),
                       ["server", "api", "backend", "sysfs_root"])

    raw = sys.stdin.read()
    container_config = json.loads(raw) if raw.strip() else {}

    if args.server:
        from kubegpu_tpu.runtime.server import request_create_container

        out = request_create_container(args.server, args.pod, args.container,
                                       container_config)
    else:
        from kubegpu_tpu.cluster.httpapi import HTTPAPIClient
        from kubegpu_tpu.cmd.node_agent import build_manager
        from kubegpu_tpu.runtime.hook import TPURuntimeHook

        client = HTTPAPIClient(args.api)
        mgr = build_manager(args.backend, args.sysfs_root)
        hook = TPURuntimeHook(client, mgr)
        out = hook.create_container(args.pod, args.container, container_config)
    json.dump(out, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

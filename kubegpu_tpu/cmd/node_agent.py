"""``kgtpu-node-agent``: device discovery + advertiser + CRI hook server.

Reference: `crishim/pkg/app/app.go` — flag parsing, device plugin loading
(here: backend selection), advertiser startup — plus the persistent CRI
interception endpoint (`docker_container.go:115-191`: the reference's shim
is a long-running gRPC CRI server, not a per-container CLI). The agent
serves the rewrite endpoint on ``--cri-socket``/``--cri-port``;
``kgtpu-cri-hook`` is the thin client a runtime's OCI-hook config execs.
"""

from __future__ import annotations

import argparse
import signal
import socket
import threading

from kubegpu_tpu.cluster.httpapi import HTTPAPIClient
from kubegpu_tpu.cmd import common
from kubegpu_tpu.node.advertiser import DeviceAdvertiser
from kubegpu_tpu.node.manager import DevicesManager, TPUDeviceManager


def build_manager(backend_kind: str, sysfs_root: str,
                  plugins_dir: str | None = None) -> DevicesManager:
    mgr = DevicesManager()
    mgr.add_device(TPUDeviceManager(common.build_backend(backend_kind, sysfs_root)))
    if plugins_dir:
        # the reference's --cridevices seam (`crishim/pkg/app/app.go:33-38`)
        mgr.add_devices_from_plugins(plugins_dir)
    mgr.start()
    return mgr


def _primary_address() -> str | None:
    """The routable primary IP, via a connected UDP socket (no packet
    is sent). gethostbyname(hostname) is wrong here: stock /etc/hosts
    maps the hostname to 127.0.1.1, and advertising a loopback address
    cluster-wide would make every remote gang member dial itself. On
    failure advertise nothing — the hook then falls back to the node
    name, which may resolve."""
    probe = None
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.connect(("10.255.255.255", 1))
        return str(probe.getsockname()[0])
    except OSError:
        # the probe could not determine a route; the socket (when it
        # was created at all) is still closed below
        return None
    finally:
        if probe is not None:
            probe.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--api", default="http://127.0.0.1:8070")
    parser.add_argument("--wire", choices=("stream", "json"),
                        default="stream",
                        help="control-plane wire (stream negotiates down "
                             "to json against an older apiserver)")
    parser.add_argument("--node-name", default=None,
                        help="defaults to the hostname, like kubelet")
    parser.add_argument("--node-address", default=None,
                        help="routable address advertised for this node "
                             "(gang coordinators resolve through it); "
                             "defaults to the host's primary IP")
    parser.add_argument("--backend", default="native",
                        choices=["native", "fake-v5p", "fake-single"])
    parser.add_argument("--sysfs-root", default="/sys/class")
    parser.add_argument("--device-plugins-dir", default=None,
                        help="load extra device plugins (*.py exporting "
                             "create_device_plugin) from this directory, "
                             "like the reference's --cridevices")
    parser.add_argument("--advertise-interval", type=float, default=20.0)
    parser.add_argument("--retry-interval", type=float, default=5.0)
    parser.add_argument("--register-node", action="store_true",
                        help="create the node object if absent")
    parser.add_argument("--healthz-port", type=int, default=0)
    parser.add_argument("--cri-socket", default=None,
                        help="serve the CRI create-container rewrite "
                             "endpoint on this unix socket")
    parser.add_argument("--cri-port", type=int, default=None,
                        help="serve the CRI rewrite endpoint on this "
                             "loopback TCP port (0 = ephemeral)")
    parser.add_argument("--launch-log-dir", default=None,
                        help="directory for supervised workloads' "
                             "stdout/stderr (default: discard)")
    parser.add_argument("--config", default=None)
    args = parser.parse_args(argv)
    common.merge_flags(args, common.load_config(args.config),
                       ["api", "node_name", "node_address", "backend",
                        "sysfs_root", "cri_socket", "cri_port"])

    node_name = args.node_name or socket.gethostname()
    client = HTTPAPIClient(args.api, wire=args.wire)
    if args.register_node:
        try:
            client.get_node(node_name)
        except KeyError:
            client.create_node({"metadata": {"name": node_name}})

    address = args.node_address or _primary_address()
    mgr = build_manager(args.backend, args.sysfs_root,
                        args.device_plugins_dir)
    adv = DeviceAdvertiser(client, mgr, node_name, address=address)
    adv.start(interval_s=args.advertise_interval, retry_s=args.retry_interval)
    # /healthz goes unhealthy when advertising has been failing longer
    # than the advertise interval — a dead/blocked advertise loop is a
    # dead node as far as the scheduler's lifecycle controller is
    # concerned, and the agent should say so before the scheduler does.
    common.serve_health(args.healthz_port,
                        extra_status=adv.healthy)

    cri_server = None
    supervisor = None
    if args.cri_socket or args.cri_port is not None:
        from kubegpu_tpu.runtime.hook import TPURuntimeHook
        from kubegpu_tpu.runtime.launcher import WorkloadSupervisor
        from kubegpu_tpu.runtime.server import CRIHookServer

        hook = TPURuntimeHook(client, mgr)
        supervisor = WorkloadSupervisor(api=client,
                                        log_dir=args.launch_log_dir)
        cri_server = CRIHookServer(
            hook, unix_socket=args.cri_socket,
            port=None if args.cri_socket else args.cri_port,
            supervisor=supervisor)
        cri_server.start()
        where = args.cri_socket or f"127.0.0.1:{cri_server.port}"
        print(f"cri-hook serving on {where}", flush=True)
    print(f"node-agent advertising {node_name} -> {args.api}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    # server first: no new launches may arrive once the supervisor has
    # begun killing containers, or they'd orphan un-reaped
    if cri_server is not None:
        cri_server.stop()
    if supervisor is not None:
        supervisor.shutdown()
    adv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Shared CLI plumbing: config files, health/metrics endpoints, backends.

Mirrors the reference's flag/config conventions (SURVEY.md §6): a
``--config`` file (JSON, or YAML when available) merged under explicit
flags, and healthz + Prometheus metrics HTTP servers
(`cmd/app/server.go:405-417,463-476`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubegpu_tpu import metrics


def load_config(path: str | None) -> dict:
    if not path:
        return {}
    with open(path) as f:
        text = f.read()
    try:
        parsed = json.loads(text)
    except ValueError:
        try:
            import yaml  # optional

            parsed = yaml.safe_load(text)
        except ImportError:
            raise ValueError(f"{path} is not JSON and PyYAML is unavailable")
    if not isinstance(parsed, dict):
        raise ValueError(f"{path}: config must be a mapping, got "
                         f"{type(parsed).__name__}")
    return parsed


def merge_flags(args, config: dict, keys: list) -> None:
    """Config file fills in any flag left at its parser default (explicit
    flags win, like componentconfig vs legacy flags)."""
    for key in keys:
        if key in config and getattr(args, key, None) in (None, ""):
            setattr(args, key, config[key])


# The exposition itself lives in metrics.py now (so the apiserver route
# table can serve /metrics without importing the CLI layer); this alias
# keeps the historic import path working.
prometheus_text = metrics.prometheus_text


def serve_health(port: int, extra_status=None, recorder=None):
    """healthz + /metrics + /metrics/history + trace/profile debug
    server; returns the server (daemon thread), or None when port <= 0.
    ``/debug/traces`` serves the process's span ring as
    Perfetto-loadable Chrome trace JSON; ``/debug/pod/<name>`` answers
    "why is this pod Pending/slow" from the same ring (``recorder``
    defaults to the process-global one); ``/debug/profile`` serves the
    sampling profiler's attribution table + collapsed stacks;
    ``/metrics/history?window_s=300`` serves the metrics time-series'
    windowed summary."""
    if port is None or port <= 0:
        return None
    from kubegpu_tpu import obs

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _json(self, obj):
            body = json.dumps(obj).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            from urllib.parse import parse_qs, unquote, urlsplit

            parts = urlsplit(self.path)
            path = parts.path
            query = {k: v[-1] for k, v in parse_qs(parts.query).items()}
            if path == "/healthz":
                ok = True
                if extra_status is not None:
                    ok = bool(extra_status())
                body = b"ok" if ok else b"unhealthy"
                self.send_response(200 if ok else 500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/metrics":
                body = prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/metrics/history":
                self._json(obs.metrics_history(
                    window_s=float(query.get("window_s", 300.0)),
                    limit=int(query.get("limit", 0))))
            elif path == "/debug/profile":
                self._json(obs.profile_status())
            elif path == "/debug/traces":
                self._json(obs.chrome_trace(recorder=recorder))
            elif path.startswith("/debug/pod/"):
                name = unquote(path[len("/debug/pod/"):])
                self._json(obs.explain_pod(name, recorder=recorder))
            else:
                self.send_response(404)
                self.end_headers()

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="health").start()
    return server


def add_observability_flags(parser) -> None:
    """The continuous-profiling + metrics-history flags every binary
    (scheduler_main / apiserver_main / simulate) shares."""
    parser.add_argument("--profile-dir", default=None,
                        help="run the sampling profiler (~125 Hz stack "
                             "sampler with role/phase/lock-wait "
                             "attribution) and dump collapsed-stack + "
                             "attribution JSON here on exit; "
                             "KGTPU_PROFILE=0 disables")
    parser.add_argument("--profile-hz", type=float, default=0.0,
                        help="sampler frequency (default 125, or "
                             "$KGTPU_PROFILE_HZ)")
    parser.add_argument("--metrics-interval-s", type=float, default=0.0,
                        help="snapshot every registered metric into a "
                             "bounded in-process ring at this interval "
                             "(serves /metrics/history; runs the "
                             "anomaly watchdog over it); 0 disables")


def start_observability(args):
    """Wire --profile-dir / --metrics-interval-s: start the sampler and
    the metrics time-series (with the anomaly watchdog attached).
    Returns an idempotent ``stop()`` that tears both down and writes
    the profile dump."""
    from kubegpu_tpu.obs import profile, timeseries

    profile_dir = getattr(args, "profile_dir", None)
    interval = getattr(args, "metrics_interval_s", 0.0) or 0.0
    sampler = None
    series = None
    installed_probe = False
    if profile_dir and profile.enabled():
        # remember whether THIS call flipped the factories: stop() must
        # restore raw locks then (an in-process caller keeps profiling-
        # free locks after the window), but never uninstall a probe an
        # enclosing profiled section still owns
        installed_probe = (not profile.lock_probe_installed()
                           and profile.install_lock_probe())
        sampler = profile.start_profiler(
            hz=getattr(args, "profile_hz", 0.0) or None)
    if interval > 0:
        series = timeseries.start_timeseries(
            interval, watchdog=timeseries.Watchdog())
    state = {"done": False}

    def stop():
        if state["done"]:
            return
        state["done"] = True
        if sampler is not None:
            profile.stop_and_dump(profile_dir)
        if installed_probe:
            profile.uninstall_lock_probe()
        if series is not None:
            timeseries.stop_timeseries()

    return stop


def build_backend(kind: str, sysfs_root: str):
    """Device backend selection (the ``--cridevices`` analogue)."""
    if kind == "native":
        from kubegpu_tpu.node.enumerator import NativeTPUBackend

        return NativeTPUBackend(sysfs_root)
    if kind == "fake-v5p":
        from kubegpu_tpu.node.fake import FakeTPUBackend

        return FakeTPUBackend()
    if kind == "fake-single":
        from kubegpu_tpu.node.fake import FakeTPUBackend, single_chip_inventory

        return FakeTPUBackend(single_chip_inventory())
    raise ValueError(f"unknown backend {kind!r}")

"""Shared CLI plumbing: config files, health/metrics endpoints, backends.

Mirrors the reference's flag/config conventions (SURVEY.md §6): a
``--config`` file (JSON, or YAML when available) merged under explicit
flags, and healthz + Prometheus metrics HTTP servers
(`cmd/app/server.go:405-417,463-476`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubegpu_tpu import metrics


def load_config(path: str | None) -> dict:
    if not path:
        return {}
    with open(path) as f:
        text = f.read()
    try:
        parsed = json.loads(text)
    except ValueError:
        try:
            import yaml  # optional

            parsed = yaml.safe_load(text)
        except ImportError:
            raise ValueError(f"{path} is not JSON and PyYAML is unavailable")
    if not isinstance(parsed, dict):
        raise ValueError(f"{path}: config must be a mapping, got "
                         f"{type(parsed).__name__}")
    return parsed


def merge_flags(args, config: dict, keys: list) -> None:
    """Config file fills in any flag left at its parser default (explicit
    flags win, like componentconfig vs legacy flags)."""
    for key in keys:
        if key in config and getattr(args, key, None) in (None, ""):
            setattr(args, key, config[key])


def _histogram_lines(h, labels: str = "") -> list:
    """One histogram's exposition lines; ``labels`` is a pre-rendered
    ``key="value",`` prefix for labeled children."""
    lines = []
    cumulative = 0
    for bound, count in zip(h.buckets, h.counts):
        cumulative += count
        lines.append(f'{h.name}_bucket{{{labels}le="{bound:g}"}} '
                     f"{cumulative}")
    lines.append(f'{h.name}_bucket{{{labels}le="+Inf"}} {h.n}')
    suffix = f"{{{labels[:-1]}}}" if labels else ""
    lines.append(f"{h.name}_sum{suffix} {h.total:.6g}")
    lines.append(f"{h.name}_count{suffix} {h.n}")
    return lines


def prometheus_text() -> str:
    """Render the process's metrics in Prometheus exposition format.
    Registry-driven: iterates ``metrics.all_metrics()``, so every
    declared metric is exported — registration and exposition cannot
    drift (the omission class the metric-registration analysis rule now
    closes statically)."""
    lines = []
    for m in metrics.all_metrics():
        if isinstance(m, metrics.LabeledHistogram):
            lines.append(f"# TYPE {m.name} histogram")
            for value, child in m.children():
                lines.extend(_histogram_lines(
                    child, f'{m.label}="{value}",'))
        elif isinstance(m, metrics.Histogram):
            lines.append(f"# TYPE {m.name} histogram")
            lines.extend(_histogram_lines(m))
        elif isinstance(m, metrics.LabeledCounter):
            lines.append(f"# TYPE {m.name} counter")
            for values, child in m.children():
                rendered = ",".join(
                    f'{k}="{v}"' for k, v in zip(m.label_names, values))
                lines.append(f"{m.name}{{{rendered}}} {child.value}")
        elif isinstance(m, metrics.Counter):
            lines.append(f"# TYPE {m.name} counter")
            lines.append(f"{m.name} {m.value}")
        elif isinstance(m, metrics.Gauge):
            lines.append(f"# TYPE {m.name} gauge")
            lines.append(f"{m.name} {m.value}")
    return "\n".join(lines) + "\n"


def serve_health(port: int, extra_status=None, recorder=None):
    """healthz + /metrics + trace-debug server; returns the server
    (daemon thread), or None when port <= 0. ``/debug/traces`` serves
    the process's span ring as Perfetto-loadable Chrome trace JSON;
    ``/debug/pod/<name>`` answers "why is this pod Pending/slow" from
    the same ring (``recorder`` defaults to the process-global one)."""
    if port is None or port <= 0:
        return None
    from kubegpu_tpu import obs

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _json(self, obj):
            body = json.dumps(obj).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                ok = True
                if extra_status is not None:
                    ok = bool(extra_status())
                body = b"ok" if ok else b"unhealthy"
                self.send_response(200 if ok else 500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/metrics":
                body = prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/debug/traces":
                self._json(obs.chrome_trace(recorder=recorder))
            elif self.path.startswith("/debug/pod/"):
                from urllib.parse import unquote

                name = unquote(self.path[len("/debug/pod/"):])
                self._json(obs.explain_pod(name, recorder=recorder))
            else:
                self.send_response(404)
                self.end_headers()

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="health").start()
    return server


def build_backend(kind: str, sysfs_root: str):
    """Device backend selection (the ``--cridevices`` analogue)."""
    if kind == "native":
        from kubegpu_tpu.node.enumerator import NativeTPUBackend

        return NativeTPUBackend(sysfs_root)
    if kind == "fake-v5p":
        from kubegpu_tpu.node.fake import FakeTPUBackend

        return FakeTPUBackend()
    if kind == "fake-single":
        from kubegpu_tpu.node.fake import FakeTPUBackend, single_chip_inventory

        return FakeTPUBackend(single_chip_inventory())
    raise ValueError(f"unknown backend {kind!r}")

"""Shared CLI plumbing: config files, health/metrics endpoints, backends.

Mirrors the reference's flag/config conventions (SURVEY.md §6): a
``--config`` file (JSON, or YAML when available) merged under explicit
flags, and healthz + Prometheus metrics HTTP servers
(`cmd/app/server.go:405-417,463-476`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubegpu_tpu import metrics


def load_config(path: str | None) -> dict:
    if not path:
        return {}
    with open(path) as f:
        text = f.read()
    try:
        parsed = json.loads(text)
    except ValueError:
        try:
            import yaml  # optional

            parsed = yaml.safe_load(text)
        except ImportError:
            raise ValueError(f"{path} is not JSON and PyYAML is unavailable")
    if not isinstance(parsed, dict):
        raise ValueError(f"{path}: config must be a mapping, got "
                         f"{type(parsed).__name__}")
    return parsed


def merge_flags(args, config: dict, keys: list) -> None:
    """Config file fills in any flag left at its parser default (explicit
    flags win, like componentconfig vs legacy flags)."""
    for key in keys:
        if key in config and getattr(args, key, None) in (None, ""):
            setattr(args, key, config[key])


def prometheus_text() -> str:
    """Render the process's metrics in Prometheus exposition format."""
    lines = []
    for h in (metrics.E2E_SCHEDULING_LATENCY, metrics.ALGORITHM_LATENCY,
              metrics.BINDING_LATENCY, metrics.BIND_LATENCY_MS,
              metrics.WAL_FSYNC_MS):
        lines.append(f"# TYPE {h.name} histogram")
        cumulative = 0
        for bound, count in zip(h.buckets, h.counts):
            cumulative += count
            lines.append(f'{h.name}_bucket{{le="{bound:g}"}} {cumulative}')
        lines.append(f'{h.name}_bucket{{le="+Inf"}} {h.n}')
        lines.append(f"{h.name}_sum {h.total:.6g}")
        lines.append(f"{h.name}_count {h.n}")
    for c in (metrics.SCHEDULE_ATTEMPTS, metrics.SCHEDULE_FAILURES,
              metrics.PREEMPTION_VICTIMS, metrics.NODE_LOST,
              metrics.EVICTIONS, metrics.WATCH_COALESCED,
              metrics.SCHED_CONFLICTS, metrics.LEASE_TRANSITIONS):
        lines.append(f"# TYPE {c.name} counter")
        lines.append(f"{c.name} {c.value}")
    for g in (metrics.NODE_READY, metrics.BIND_INFLIGHT,
              metrics.WATCH_BATCH_SIZE, metrics.WAL_SNAPSHOT_BYTES):
        lines.append(f"# TYPE {g.name} gauge")
        lines.append(f"{g.name} {g.value}")
    return "\n".join(lines) + "\n"


def serve_health(port: int, extra_status=None):
    """healthz + /metrics server; returns the server (daemon thread), or
    None when port <= 0."""
    if port is None or port <= 0:
        return None

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_GET(self):
            if self.path == "/healthz":
                ok = True
                if extra_status is not None:
                    ok = bool(extra_status())
                body = b"ok" if ok else b"unhealthy"
                self.send_response(200 if ok else 500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/metrics":
                body = prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="health").start()
    return server


def build_backend(kind: str, sysfs_root: str):
    """Device backend selection (the ``--cridevices`` analogue)."""
    if kind == "native":
        from kubegpu_tpu.node.enumerator import NativeTPUBackend

        return NativeTPUBackend(sysfs_root)
    if kind == "fake-v5p":
        from kubegpu_tpu.node.fake import FakeTPUBackend

        return FakeTPUBackend()
    if kind == "fake-single":
        from kubegpu_tpu.node.fake import FakeTPUBackend, single_chip_inventory

        return FakeTPUBackend(single_chip_inventory())
    raise ValueError(f"unknown backend {kind!r}")

"""``kgtpu-simulate``: one-process cluster demo.

Spins up the API server, N fake v5p hosts with advertisers, and the
scheduler; submits a workload mix (plain, HBM-floored, contiguous, and a
gang) and prints the placements plus what each container would receive
from the runtime hook.
"""

from __future__ import annotations

import argparse
import json

from kubegpu_tpu.cluster.apiserver import InMemoryAPIServer
from kubegpu_tpu.core import codec, grammar
from kubegpu_tpu.core.types import ContainerInfo, PodInfo
from kubegpu_tpu.node.advertiser import DeviceAdvertiser
from kubegpu_tpu.node.fake import FakeTPUBackend, v5p_host_inventory
from kubegpu_tpu.node.manager import DevicesManager, TPUDeviceManager
from kubegpu_tpu.runtime.hook import TPURuntimeHook
from kubegpu_tpu.scheduler.core import Scheduler
from kubegpu_tpu.scheduler.gang import RESOURCE_GANG, RESOURCE_GANG_SIZE
from kubegpu_tpu.scheduler.registry import DevicesScheduler
from kubegpu_tpu.scheduler.tpu_scheduler import RESOURCE_CONTIGUOUS, TPUScheduler


def make_pod(name, numchips, pod_requests=None, hbm=0):
    pi = PodInfo(name=name, requests=dict(pod_requests or {}))
    reqs = {grammar.RESOURCE_NUM_CHIPS: numchips}
    if hbm:
        reqs[grammar.RESOURCE_HBM_PER_CHIP] = hbm
    pi.running_containers["main"] = ContainerInfo(requests=reqs)
    meta = {"name": name}
    codec.pod_info_to_annotation(meta, pi)
    return {"metadata": meta,
            "spec": {"containers": [{"name": "main",
                                     "resources": {"requests": {"cpu": "1"}}}]}}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--hosts", type=int, default=4)
    parser.add_argument("--json", action="store_true", help="machine output")
    args = parser.parse_args(argv)

    api = InMemoryAPIServer()
    hooks = {}
    origins = [(2 * (i % 2), 2 * (i // 2), 0) for i in range(args.hosts)]
    mesh_dims = (4, 2 * ((args.hosts + 1) // 2), 1)
    for i, origin in enumerate(origins):
        name = f"host{i}"
        api.create_node({"metadata": {"name": name,
                                      "labels": {"kubernetes.io/hostname":
                                                 name}},
                         "status": {"allocatable": {"cpu": "64", "pods": 100}}})
        mgr = DevicesManager()
        mgr.add_device(TPUDeviceManager(FakeTPUBackend(
            v5p_host_inventory(host_origin=origin, mesh_dims=mesh_dims))))
        mgr.start()
        DeviceAdvertiser(api, mgr, name).advertise_once()
        hooks[name] = TPURuntimeHook(api, mgr)

    ds = DevicesScheduler()
    ds.add_device(TPUScheduler())
    sched = Scheduler(api, ds)

    api.create_pod(make_pod("plain-2chip", 2))
    api.create_pod(make_pod("hbm-floored", 1, hbm=90 * 2**30))
    api.create_pod(make_pod("contig-4chip", 4,
                            pod_requests={RESOURCE_CONTIGUOUS: 1}))
    # volume-bound pod: the PV's node affinity pins it to host1 (which
    # the mixed pods leave a chip on), so placement is visibly steered
    # and the claim flips to Bound at schedule time — without stealing a
    # chip the gang needs
    pinned_host = f"host{min(1, args.hosts - 1)}"
    api.create_pvc({"metadata": {"name": "demo-claim"},
                    "spec": {"resources": {"requests": {"storage": "10Gi"}},
                             "storageClassName": ""}})
    api.create_pv({"metadata": {"name": "demo-vol"},
                   "spec": {"capacity": {"storage": "10Gi"},
                            "storageClassName": "",
                            "nodeAffinity": {"required": {
                                "nodeSelectorTerms": [{"matchExpressions": [
                                    {"key": "kubernetes.io/hostname",
                                     "operator": "In",
                                     "values": [pinned_host]}]}]}}}})
    vol_pod = make_pod("vol-1chip", 1)
    vol_pod["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": "demo-claim"}}]
    api.create_pod(vol_pod)
    gang_n = min(2, args.hosts)
    for i in range(gang_n):
        api.create_pod(make_pod(f"gang-{i}", 4,
                                pod_requests={RESOURCE_GANG: 1,
                                              RESOURCE_GANG_SIZE: gang_n}))
    sched.run_until_idle()

    rows = []
    for pod in api.list_pods():
        name = pod["metadata"]["name"]
        node = pod.get("spec", {}).get("nodeName")
        env = {}
        if node:
            cfg = hooks[node].create_container(name, "main", {})
            env = {e["key"]: e["value"] for e in cfg.get("envs", [])}
        row = {"pod": name, "node": node or "<pending>",
               "chips": env.get("TPU_CHIP_IDS", ""),
               "bounds": env.get("TPU_PROCESS_BOUNDS", "")}
        if name == "vol-1chip":
            row["volume"] = api.get_pvc("demo-claim")["spec"] \
                .get("volumeName", "<unbound>")
        rows.append(row)

    if args.json:
        print(json.dumps(rows, indent=2))
    else:
        width = max(len(r["pod"]) for r in rows) + 2
        print(f"{'POD':<{width}}{'NODE':<10}{'CHIPS':<28}{'BOUNDS':<8}VOLUME")
        for r in rows:
            print(f"{r['pod']:<{width}}{r['node']:<10}{r['chips']:<28}"
                  f"{r['bounds']:<8}{r.get('volume', '')}")
    sched.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

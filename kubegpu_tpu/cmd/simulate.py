"""``kgtpu-simulate``: one-process cluster demo.

Spins up the API server, N fake v5p hosts with advertisers, and the
scheduler; submits a workload mix (plain, HBM-floored, contiguous, and a
gang) and prints the placements plus what each container would receive
from the runtime hook. ``--schedulers N`` runs N optimistic scheduler
replicas over the same API server, each owning a pod-name-hash shard
under a lease (the HA control plane in one process).

``--chaos [node-loss]`` runs the node-loss recovery scenario instead: a
4-host cluster under a seeded chaos transport, a 2-node gang placed, one
node agent killed mid-gang — measuring how long the NodeLifecycle
controller takes to detect the loss, evict the gang, and rebind it
entirely on surviving nodes with zero leaked chips.

``--chaos chip-kill`` runs the partial-hardware-failure scenario: one
chip ALLOCATED to a running gang dies (seeded fault injector); the
advertiser stamps the failure, the RepairController checkpoints and
gang-evicts, and the scheduler re-plans onto healthy chips — zero
leaked chips, zero double-binds, zero relists, the dead chip excluded.

``--chaos-ha`` runs the HA control-plane chaos scenario: two scheduler
replicas over a WAL-backed HTTP apiserver; replica 0 is killed
mid-stream (its shard's work is stolen via lease vacancy), then the
apiserver process state is torn down and recovered from the WAL on the
same port — every pod must place exactly once (zero leaked chips, zero
double-binds) and the surviving replica's watch must resume seq-exact
(no relist) across the restart.
"""

from __future__ import annotations

import argparse
import json
import time

from kubegpu_tpu import metrics, obs
from kubegpu_tpu.cluster.apiserver import InMemoryAPIServer
from kubegpu_tpu.core import codec, grammar
from kubegpu_tpu.core.types import ContainerInfo, PodInfo
from kubegpu_tpu.node.advertiser import DeviceAdvertiser
from kubegpu_tpu.node.fake import FakeTPUBackend, v5p_host_inventory
from kubegpu_tpu.node.manager import DevicesManager, TPUDeviceManager
from kubegpu_tpu.runtime.hook import TPURuntimeHook
from kubegpu_tpu.scheduler.core import Scheduler
from kubegpu_tpu.scheduler.gang import RESOURCE_GANG, RESOURCE_GANG_SIZE
from kubegpu_tpu.scheduler.registry import DevicesScheduler
from kubegpu_tpu.scheduler.tpu_scheduler import RESOURCE_CONTIGUOUS, TPUScheduler


def make_pod(name, numchips, pod_requests=None, hbm=0):
    pi = PodInfo(name=name, requests=dict(pod_requests or {}))
    reqs = {grammar.RESOURCE_NUM_CHIPS: numchips}
    if hbm:
        reqs[grammar.RESOURCE_HBM_PER_CHIP] = hbm
    pi.running_containers["main"] = ContainerInfo(requests=reqs)
    meta = {"name": name}
    codec.pod_info_to_annotation(meta, pi)
    return {"metadata": meta,
            "spec": {"containers": [{"name": "main",
                                     "resources": {"requests": {"cpu": "1"}}}]}}


def _fit_cache_summary() -> dict:
    """Fit-memo effectiveness of the run (metrics.py counters): a dead
    cache (zero hits on a multi-pod workload) is a perf regression the
    summary makes visible without a profiler."""
    return {"hits": metrics.FIT_CACHE_HITS.value,
            "misses": metrics.FIT_CACHE_MISSES.value,
            "invalidations": metrics.FIT_CACHE_INVALIDATIONS.value,
            # vectorized scheduling core: masked passes + how many
            # node-verdicts fell through to the scalar path (the
            # fallback rate on a uniform fleet is CI-gated < 5%)
            "vector_passes": metrics.FIT_VECTOR_PASS_MS.n,
            "vector_pass_p50_ms": round(
                metrics.FIT_VECTOR_PASS_MS.percentile(0.5), 4),
            "scalar_fallback": metrics.FIT_SCALAR_FALLBACK.value,
            "verdict_timeouts": metrics.FIT_VERDICT_TIMEOUTS.value}


def _batch_summary() -> dict:
    """Whole-backlog batch scheduling health (metrics.py): cycles run,
    pods and equivalence classes per cycle, and the rolling bound-pod
    throughput gauge — a run where the batch path never engaged (zero
    cycles under a multi-pod workload with KGTPU_BATCH unset) is the
    regression this summary makes visible."""
    cycles = metrics.SCHED_BATCH_SIZE.n
    return {"cycles": cycles,
            "pods_per_cycle_mean": round(
                metrics.SCHED_BATCH_SIZE.total / max(cycles, 1), 2),
            "classes_per_cycle_mean": round(
                metrics.SCHED_BATCH_CLASSES.total / max(cycles, 1), 2),
            "throughput_pods_per_s": round(
                metrics.SCHED_THROUGHPUT.value, 1)}


def _serving_summary() -> dict:
    """Fused serving data plane health (metrics.py): TTFT/ITL latency
    percentiles from the chunk-boundary histograms plus the demand
    gauges the autoscaler would key on. Only attached when a serving
    workload actually ran in-process (the chaos/placement scenarios
    schedule pods, they don't decode), so an all-zero block never
    muddies a scheduler-only doc."""
    return {"requests": metrics.SERVE_TTFT_MS.n,
            "ttft_p50_ms": round(metrics.SERVE_TTFT_MS.percentile(0.5), 3),
            "ttft_p99_ms": round(metrics.SERVE_TTFT_MS.percentile(0.99), 3),
            "itl_p50_ms": round(metrics.SERVE_ITL_MS.percentile(0.5), 3),
            "itl_p99_ms": round(metrics.SERVE_ITL_MS.percentile(0.99), 3),
            "queue_depth": metrics.SERVE_QUEUE_DEPTH.value,
            "slot_utilization": round(
                metrics.SERVE_SLOT_UTILIZATION.value, 3)}


def _data_plane_summary() -> dict:
    """Binder-pipeline, watch-batching, and wire-transport health
    (metrics.py): bind latency p50/count, live binder depth, last watch
    batch size, events the server coalesced away before delivery, bytes
    per wire+direction, frame codec cost, and stream-push lag."""
    return {"bind_p50_ms": round(metrics.BIND_LATENCY_MS.percentile(0.5), 3),
            "bind_count": metrics.BIND_LATENCY_MS.n,
            "bind_inflight": metrics.BIND_INFLIGHT.value,
            "watch_batch_size": metrics.WATCH_BATCH_SIZE.value,
            "watch_coalesced_total": metrics.WATCH_COALESCED.value,
            "transport_bytes_total": {
                f"{wire}_{direction}": child.value
                for (wire, direction), child
                in metrics.TRANSPORT_BYTES.children()},
            "frame_encode_p50_ms": round(
                metrics.FRAME_ENCODE_MS.percentile(0.5), 4),
            "frame_decode_p50_ms": round(
                metrics.FRAME_DECODE_MS.percentile(0.5), 4),
            "watch_push_lag_p50_ms": round(
                metrics.WATCH_PUSH_LAG_MS.percentile(0.5), 4)}


def _ha_summary() -> dict:
    """HA control-plane health (metrics.py): commits the apiserver's
    conflict arbiter refused, lease leadership transitions, and the
    WAL's per-append fsync cost + last snapshot size."""
    return {"sched_conflicts_total": metrics.SCHED_CONFLICTS.value,
            "lease_transitions_total": metrics.LEASE_TRANSITIONS.value,
            "wal_fsync_p50_ms": round(metrics.WAL_FSYNC_MS.percentile(0.5), 4),
            "wal_appends": metrics.WAL_FSYNC_MS.n,
            "wal_snapshot_bytes": metrics.WAL_SNAPSHOT_BYTES.value}


def _apf_summary() -> dict:
    """Multi-tenant front-door health (metrics.py): queue wait of
    admitted requests, rejects per band (system must stay zero — it is
    exempt by construction), and pods the DRF chip gate parked."""
    return {"apf_queue_wait_p50_ms": round(
                metrics.APF_QUEUE_WAIT_MS.percentile(0.5), 4),
            "apf_queue_wait_p99_ms": round(
                metrics.APF_QUEUE_WAIT_MS.percentile(0.99), 4),
            "apf_rejects_total": {
                band: child.value for (band,), child
                in metrics.APF_REJECTS.children()},
            "quota_parked_total": metrics.QUOTA_PARKED.value}


def _proxy_summary(replicas) -> dict:
    """Watch-cache proxy tier health (cluster/proxy.py + metrics.py):
    per-server request split (the flood-absorption evidence), live
    downstream watcher counts, the upstream hop's push lag, and the
    upstream leg's byte attribution (wire="proxy")."""
    from kubegpu_tpu.cluster import stream

    return {"api_requests_total": {
                server: child.value for (server,), child
                in metrics.API_REQUESTS.children()},
            "downstream_watchers": {
                r.name: r.downstream_watchers() for r in replicas},
            "proxy_upstream_lag_p50_ms": round(
                metrics.PROXY_UPSTREAM_LAG_MS.percentile(0.5), 3),
            "proxy_upstream_lag_p99_ms": round(
                metrics.PROXY_UPSTREAM_LAG_MS.percentile(0.99), 3),
            "upstream_wire_bytes": {
                dir_: child.value for (wire, dir_), child
                in metrics.TRANSPORT_BYTES.children()
                if wire == stream.WIRE_PROXY}}


def _gang_chips(api, name):
    """Chip-id list a bound pod's allocation annotation pins — the raw
    persisted decision, read back via the codec's decode half."""
    pi = codec.annotation_to_pod_info(
        api.get_pod(name).get("metadata") or {})
    chips = []
    for cont in pi.running_containers.values():
        for path in cont.allocate_from.values():
            cid = grammar.chip_id_from_path(path)
            if cid:
                chips.append(cid)
    return chips


def run_chaos_scenario(seed: int = 0, lost_after_s: float = 0.9,
                       stale_after_s: float = 0.3,
                       advertise_interval_s: float = 0.1,
                       drop: float = 0.05):
    """Kill one node agent of a 2-node gang under a seeded chaos
    transport; measure detection + gang eviction + rebind time.

    Returns a dict with ``recovery_ms``, the victim node, the chaos fault
    counts, and the final placements — raises if the gang fails to place,
    leaks chips, or lands back on the lost node.
    """
    from kubegpu_tpu.cluster.chaos import ChaosConfig, ChaosNetwork
    from kubegpu_tpu.scheduler.lifecycle import NodeLifecycle

    net = ChaosNetwork(seed=seed)
    api = InMemoryAPIServer()
    # 2x2 grid of 4-chip hosts: any surviving pair adjacent to each other
    # can host the re-planned 8-chip gang block
    origins = [(0, 0, 0), (2, 0, 0), (0, 2, 0), (2, 2, 0)]
    advs = {}
    for i, origin in enumerate(origins):
        name = f"host{i}"
        api.create_node({"metadata": {"name": name},
                         "status": {"allocatable": {"cpu": "64",
                                                    "pods": 100}}})
        mgr = DevicesManager()
        mgr.add_device(TPUDeviceManager(FakeTPUBackend(
            v5p_host_inventory(host_origin=origin, mesh_dims=(4, 4, 1)))))
        mgr.start()
        adv = DeviceAdvertiser(
            net.proxy(api, f"agent-{name}", ChaosConfig(drop=drop)),
            mgr, name)
        adv.start(interval_s=advertise_interval_s, retry_s=0.03)
        advs[name] = adv
    ds = DevicesScheduler()
    ds.add_device(TPUScheduler())
    # chaos scoped to verbs every failure path requeues through cleanly
    # (list_pods is excluded: the Scheduler constructor's cold-start sync
    # reads it with no retry layer above the in-memory client)
    sched_api = net.proxy(api, "scheduler", ChaosConfig(
        drop=drop, delay=0.2, delay_s=0.002,
        verbs={"bind_many", "bind_pod", "update_pod_annotations",
               "record_event", "get_pod"}))
    sched = Scheduler(sched_api, ds)
    sched.start()
    lifecycle = NodeLifecycle(
        net.proxy(api, "lifecycle", ChaosConfig(drop=drop)),
        stale_after_s=stale_after_s, lost_after_s=lost_after_s)
    lifecycle.start(interval_s=0.05)
    names = ["chaos-gang-0", "chaos-gang-1"]
    try:
        for name in names:
            api.create_pod(make_pod(name, 4,
                                    pod_requests={RESOURCE_GANG: 77,
                                                  RESOURCE_GANG_SIZE: 2}))

        def placements(deadline_s, forbidden=None):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                bound = {}
                for name in names:
                    try:
                        node = api.get_pod(name)["spec"].get("nodeName")
                    except KeyError:
                        # mid-eviction: deleted, replacement not created
                        # yet (the create may even have been chaos-dropped
                        # and be parked for the next lifecycle tick)
                        break
                    if not node or (forbidden and node == forbidden):
                        break
                    bound[name] = node
                else:
                    return bound
                time.sleep(0.02)
            raise RuntimeError(
                f"gang did not (re)bind in {deadline_s}s "
                f"(forbidden={forbidden}, faults={net.faults})")

        first = placements(20.0)
        victim = first[names[0]]
        advs[victim].stop()  # the node agent dies mid-gang
        t0 = time.monotonic()
        final = placements(30.0, forbidden=victim)
        recovery_ms = (time.monotonic() - t0) * 1e3
        chips = {name: _gang_chips(api, name) for name in names}
        all_chips = [c for cs in chips.values() for c in cs]
        if sorted(len(c) for c in chips.values()) != [4, 4] or \
                len(set(all_chips)) != 8:
            raise RuntimeError(f"chip leak/short allocation: {chips}")
        doc = {"recovery_ms": round(recovery_ms, 1),
               "victim": victim,
               "first_placement": first,
               "final_placement": final,
               "evicted_pods": lifecycle.evicted_total,
               "fit_cache": _fit_cache_summary(),
               "batch": _batch_summary(),
               "data_plane": _data_plane_summary(),
               "chaos_faults": {f"{c}:{k}": n for (c, k), n
                                in sorted(net.faults.items())}}
        if metrics.SERVE_TTFT_MS.n:
            doc["serving"] = _serving_summary()
        return doc
    finally:
        lifecycle.stop()
        for adv in advs.values():
            adv.stop()
        sched.stop()


def run_chip_kill_scenario(seed: int = 0,
                           advertise_interval_s: float = 0.05,
                           deadline_s: float = 30.0):
    """Kill one ALLOCATED chip under a running gang; measure the device-
    fault repair path end to end: advertiser stamps the failed chip,
    the RepairController checkpoints + gang-evicts, the scheduler
    re-plans onto healthy chips.

    Returns a dict with ``recovery_ms``, the victim (node, chip), and
    the placements — raises if the gang fails to recover, lands back on
    the dead chip, leaks or double-binds chips, the checkpoint signal
    never fired, or the watch relisted.
    """
    import random

    from kubegpu_tpu.cluster.chaos import DeviceChaos
    from kubegpu_tpu.scheduler.repair import RepairController

    api = InMemoryAPIServer()
    # 2x2 grid of 4-chip hosts: after one chip dies on the gang's pair,
    # the OTHER adjacent pair still offers a contiguous 8-chip block
    origins = [(0, 0, 0), (2, 0, 0), (0, 2, 0), (2, 2, 0)]
    backends = {}
    advs = {}
    for i, origin in enumerate(origins):
        name = f"host{i}"
        api.create_node({"metadata": {"name": name},
                         "status": {"allocatable": {"cpu": "64",
                                                    "pods": 100}}})
        backend = FakeTPUBackend(
            v5p_host_inventory(host_origin=origin, mesh_dims=(4, 4, 1)))
        backends[name] = backend
        mgr = DevicesManager()
        mgr.add_device(TPUDeviceManager(backend))
        mgr.start()
        adv = DeviceAdvertiser(api, mgr, name)
        adv.start(interval_s=advertise_interval_s, retry_s=0.03)
        advs[name] = adv
    ds = DevicesScheduler()
    ds.add_device(TPUScheduler())
    sched = Scheduler(api, ds)
    sched.start()
    repair = RepairController(api)
    repair.start(interval_s=0.05)
    names = ["ck-gang-0", "ck-gang-1"]
    try:
        for name in names:
            api.create_pod(make_pod(name, 4,
                                    pod_requests={RESOURCE_GANG: 77,
                                                  RESOURCE_GANG_SIZE: 2}))

        def placements(deadline, forbidden_chip=None):
            stop_at = time.monotonic() + deadline
            while time.monotonic() < stop_at:
                bound = {}
                for name in names:
                    try:
                        pod = api.get_pod(name)
                    except KeyError:
                        break  # mid-eviction: replacement not landed yet
                    node = (pod.get("spec") or {}).get("nodeName")
                    if not node:
                        break
                    chips = _gang_chips(api, name)
                    if len(chips) != 4 or (
                            forbidden_chip and
                            forbidden_chip in [(node, c) for c in chips]):
                        break
                    bound[name] = node
                else:
                    return bound
                time.sleep(0.02)
            raise RuntimeError(
                f"gang did not (re)bind clean of the dead chip in "
                f"{deadline}s (forbidden={forbidden_chip}, "
                f"parked={repair.parked()})")

        first = placements(20.0)
        # deterministic victim: seeded choice among the ALLOCATED chips,
        # injected through the seeded fault injector
        allocated = sorted(
            (first[name], chip)
            for name in names for chip in _gang_chips(api, name))
        victim_node, victim_chip = random.Random(seed).choice(allocated)
        chaos = DeviceChaos(backends, seed=seed)
        chaos.kill_chip(node=victim_node, chip_id=victim_chip)
        t0 = time.monotonic()
        final = placements(deadline_s,
                           forbidden_chip=(victim_node, victim_chip))
        recovery_ms = (time.monotonic() - t0) * 1e3
        chips = _bound_chips(api, names)
        flat = [c for cs in chips.values() for c in cs]
        if sorted(len(c) for c in chips.values()) != [4, 4] or \
                len(set(flat)) != 8:
            raise RuntimeError(f"chip leak/double-bind: {chips}")
        if (victim_node, victim_chip) in set(flat):
            raise RuntimeError(f"gang rebound onto dead chip: {chips}")
        for name in names:
            events = [e for e in api.list_events(involved_name=name)
                      if e.get("reason") == "CheckpointRequested"]
            if not events:
                raise RuntimeError(
                    f"no CheckpointRequested event for {name}")
        if sched.resync_count:
            raise RuntimeError(f"watch relisted {sched.resync_count}x")
        doc = {"recovery_ms": round(recovery_ms, 1),
               "victim": {"node": victim_node, "chip": victim_chip},
               "first_placement": first,
               "final_placement": final,
               "repairs": repair.repaired_total,
               "relists": sched.resync_count,
               "injected": [list(f[:3]) for f in chaos.injected],
               "fit_cache": _fit_cache_summary(),
               "batch": _batch_summary(),
               "data_plane": _data_plane_summary()}
        if metrics.SERVE_TTFT_MS.n:
            doc["serving"] = _serving_summary()
        return doc
    finally:
        repair.stop()
        for adv in advs.values():
            adv.stop()
        sched.stop()


def _bound_chips(api, names):
    """{pod name: chip ids} for every bound pod in ``names`` — the
    read-back both chaos scenarios use to prove zero leaked chips and
    zero double-binds (global chip-id uniqueness)."""
    chips = {}
    for name in names:
        pod = api.get_pod(name)
        node = (pod.get("spec") or {}).get("nodeName")
        if not node:
            continue
        chips[name] = [(node, c) for c in _gang_chips(api, name)]
    return chips


def run_ha_chaos_scenario(pods_before: int = 6, pods_mid: int = 3,
                          pods_after: int = 3, wal_dir: str | None = None,
                          lease_ttl_s: float = 0.6,
                          deadline_s: float = 30.0,
                          wire: str = "stream"):
    """The HA control-plane chaos scenario: 2 optimistic scheduler
    replicas (shard leases + work stealing) over a WAL-backed HTTP
    apiserver. Mid-stream, replica 0 is killed — replica 1 must steal
    its shard via lease vacancy — and then the apiserver is torn down
    and recovered from its WAL on the same port — the surviving
    replica's watch must resume seq-exact (zero relists) and every pod
    (a 2-pod gang included) must place exactly once with zero leaked
    chips and zero double-binds. Raises on any violation; returns the
    scenario's accounting."""
    import shutil
    import tempfile

    from kubegpu_tpu.cluster.httpapi import HTTPAPIClient, serve_api
    from kubegpu_tpu.cluster.lease import ShardCoordinator
    from kubegpu_tpu.cluster.wal import WriteAheadLog

    tmp = wal_dir or tempfile.mkdtemp(prefix="kgtpu-wal-")
    owns_tmp = wal_dir is None
    api = InMemoryAPIServer()
    wal = WriteAheadLog(tmp, fsync=False, snapshot_every=40)
    server, url = serve_api(api, wal=wal)
    port = int(url.rsplit(":", 1)[1])
    admin = HTTPAPIClient(url, wire=wire)
    replicas = []
    submitted: list = []
    try:
        origins = [(0, 0, 0), (2, 0, 0), (0, 2, 0), (2, 2, 0)]
        for i, origin in enumerate(origins):
            name = f"host{i}"
            admin.create_node({"metadata": {"name": name},
                               "status": {"allocatable": {"cpu": "64",
                                                          "pods": 100}}})
            mgr = DevicesManager()
            mgr.add_device(TPUDeviceManager(FakeTPUBackend(
                v5p_host_inventory(host_origin=origin,
                                   mesh_dims=(4, 4, 1)))))
            mgr.start()
            DeviceAdvertiser(admin, mgr, name).advertise_once()

        def start_replica(shard):
            client = HTTPAPIClient(url, watch_batch_s=0.002,
                                   watch_kinds=("node", "pod", "pv", "pvc"),
                                   wire=wire)
            coord = ShardCoordinator(client, shard, 2, f"replica-{shard}",
                                     ttl_s=lease_ttl_s)
            ds = DevicesScheduler()
            ds.add_device(TPUScheduler())
            sched = Scheduler(client, ds, bind_async=True,
                              shard_owned=coord.owns,
                              name=f"sched-{shard}")
            coord.on_change = sched.queue.move_all_to_active
            coord.start(interval_s=lease_ttl_s / 4.0)
            sched.start()
            return client, coord, sched

        replicas.append(start_replica(0))
        replicas.append(start_replica(1))

        def submit(prefix, count, chips=1):
            from kubegpu_tpu.cluster.apiserver import Conflict

            for i in range(count):
                name = f"{prefix}-{i}"
                pod = make_pod(name, chips)
                # creates are single-shot on the transport (POST), so a
                # submission racing the apiserver restart retries HERE —
                # a Conflict means an earlier attempt landed
                for attempt in range(50):
                    try:
                        admin.create_pod(pod)
                        break
                    except Conflict:
                        break
                    except Exception:
                        if attempt == 49:
                            raise
                        time.sleep(0.1)
                submitted.append(name)

        def wait_bound(deadline=deadline_s):
            end = time.monotonic() + deadline
            pending = list(submitted)
            while time.monotonic() < end:
                try:
                    pending = [n for n in submitted
                               if not (admin.get_pod(n).get("spec") or {})
                               .get("nodeName")]
                except Exception:
                    time.sleep(0.1)  # apiserver restarting under us
                    continue
                if not pending:
                    return
                time.sleep(0.05)
            raise RuntimeError(f"pods failed to place: {pending}")

        # phase 1: both replicas place a stream (plus a gang, which must
        # route whole to one shard by gang id)
        submit("ha-a", pods_before)
        for i in range(2):
            name = f"ha-gang-{i}"
            admin.create_pod(make_pod(name, 2,
                                      pod_requests={RESOURCE_GANG: 55,
                                                    RESOURCE_GANG_SIZE: 2}))
            submitted.append(name)
        wait_bound()

        # phase 2: kill replica 0 mid-stream — its shard lease lapses
        # and replica 1 steals the work
        client0, coord0, sched0 = replicas[0]
        sched0.stop()
        coord0.stop()
        client0.close()
        replicas[0] = None
        submit("ha-b", pods_mid)
        wait_bound()

        # phase 3: apiserver crash + WAL recovery on the same port; the
        # surviving replica's watch must resume seq-exact (no relist)
        server.shutdown()
        server.server_close()
        wal.close()
        api2 = InMemoryAPIServer()
        wal2 = WriteAheadLog(tmp, fsync=False, snapshot_every=40)
        server, _ = serve_api(api2, port=port, wal=wal2)
        api = api2
        submit("ha-c", pods_after)
        wait_bound()

        client1 = replicas[1][0]
        chips = _bound_chips(admin, submitted)
        placed = {n for n in chips}
        if placed != set(submitted):
            raise RuntimeError(f"unplaced pods: {set(submitted) - placed}")
        all_claims = [c for cs in chips.values() for c in cs]
        if len(all_claims) != len(set(all_claims)):
            dups = sorted(c for c in set(all_claims)
                          if all_claims.count(c) > 1)
            raise RuntimeError(f"double-booked chips: {dups}")
        if any(not cs for cs in chips.values()):
            raise RuntimeError("a bound pod carries no chip allocation")
        if client1.relist_count != 0:
            raise RuntimeError(
                f"watch resume was not seq-exact across the apiserver "
                f"restart ({client1.relist_count} relist(s))")
        return {"placed": len(placed),
                "watch_relists": client1.relist_count,
                "wal_recovered_records": wal2.recovered_records,
                "stolen_shards": sorted(replicas[1][1].owned_shards()),
                "ha": _ha_summary()}
    finally:
        for rep in replicas:
            if rep is None:
                continue  # replica 0, already torn down in phase 2
            client, coord, sched = rep
            sched.stop()
            coord.stop()
            client.close()
        admin.close()
        server.shutdown()
        server.server_close()
        try:
            wal2.close()
        except NameError:
            wal.close()
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def tenant_pod(name, tenant, numchips=1):
    """A tenant-labeled workload pod for the multi-tenant scenarios."""
    pod = make_pod(name, numchips)
    pod["metadata"].setdefault("labels", {})["kgtpu.io/tenant"] = tenant
    return pod


def run_tenant_flood_scenario(tenants: int = 3, churn_pods: int = 12,
                              flood_threads: int = 3,
                              flood_pace_s: float = 0.005,
                              p99_ratio_limit: float = 2.0,
                              deadline_s: float = 60.0,
                              wire: str = "stream",
                              proxies: int = 0,
                              api_rate_ratio_limit: float = 1.5):
    """The ``tenant-flood`` chaos scenario: one abusive tenant floods
    pod creates through the priority-&-fairness front door while N
    well-behaved tenants churn 1-chip pods, heartbeats flow, a lease
    renews, and the node lifecycle controller watches for stale nodes.

    Measured quiet first (same cluster, no flood), then under flood.
    Raises unless ALL of:

    * every well-behaved pod still places, and the well-behaved
      create->bound p99 holds within ``p99_ratio_limit`` of quiet;
    * zero lease losses (renewals ride the exempt system band);
    * zero heartbeat-driven node evictions or Lost transitions;
    * the system band rejected nothing;
    * the DRF gate actually engaged (the abuser parked) and its bound
      chips stayed at/below its fair share (+1 pod of slack for an
      admit racing the last release);
    * the flood never starved the front door shut for well-behaved
      tenants (their churn completed before the deadline).

    With ``proxies`` > 0, shared-nothing watch-cache proxy replicas
    (cluster/proxy.py) front the apiserver, each with its own APF front
    door: tenants shard across replicas, lease renewals ride a proxy's
    forwarded (exempt) path, and the abuser becomes a READ flood aimed
    at one replica's mirror — the flood must be absorbed entirely at
    the proxy tier, so the apiserver-side request rate under flood is
    asserted flat vs quiet (within ``api_rate_ratio_limit``) and the
    fair-share/parking checks (create-flood mechanics) don't apply.

    Returns the accounting: per-phase p99s, flood counts, front-door
    and quota summaries (plus a proxy-tier summary when fronted)."""
    import threading

    from kubegpu_tpu.cluster.apf import (APFDispatcher, BandConfig,
                                         BAND_SYSTEM, BAND_WORKLOAD,
                                         TooManyRequests)
    from kubegpu_tpu.cluster.chaos import TenantFlood
    from kubegpu_tpu.cluster.httpapi import HTTPAPIClient, serve_api
    from kubegpu_tpu.cluster.lease import Elector
    from kubegpu_tpu.scheduler.lifecycle import NodeLifecycle
    from kubegpu_tpu.scheduler.quota import DRFQuotaGate

    api = InMemoryAPIServer()
    # a deliberately tight workload band: the flood must queue and shed
    # there while system traffic bypasses the front door entirely
    workload_band = dict(seats=6, queues=16, queue_len=16,
                         queue_wait_s=0.5, hand=4)
    apf = APFDispatcher(bands={
        BAND_WORKLOAD: BandConfig(**workload_band)})
    server, url = serve_api(api, apf=apf)
    admin = HTTPAPIClient(url, wire=wire)
    mgrs = []
    advs = []
    closers = []
    replicas: list = []
    elector = lifecycle = sched = None
    try:
        if proxies > 0:
            from kubegpu_tpu.cluster.proxy import WatchCacheProxy

            # each replica carries its OWN front door: a flooding
            # tenant saturates the shard it hashes to, nothing else
            replicas = [
                WatchCacheProxy(url, name=f"proxy{i}",
                                apf=APFDispatcher(bands={
                                    BAND_WORKLOAD:
                                        BandConfig(**workload_band)}))
                for i in range(proxies)]

        def shard_url(i: int) -> str:
            return replicas[i % len(replicas)].url if replicas else url
        origins = [(0, 0, 0), (2, 0, 0), (0, 2, 0), (2, 2, 0)]
        for i, origin in enumerate(origins):
            name = f"host{i}"
            admin.create_node({"metadata": {"name": name},
                               "status": {"allocatable": {"cpu": "64",
                                                          "pods": 10000}}})
            mgr = DevicesManager()
            mgr.add_device(TPUDeviceManager(FakeTPUBackend(
                v5p_host_inventory(host_origin=origin,
                                   mesh_dims=(4, 4, 1)))))
            mgr.start()
            mgrs.append(mgr)
            adv_client = HTTPAPIClient(url, wire=wire)
            closers.append(adv_client)
            adv = DeviceAdvertiser(adv_client, mgr, name)
            adv.start(interval_s=0.15, retry_s=0.05)
            advs.append(adv)

        ds = DevicesScheduler()
        ds.add_device(TPUScheduler())
        gate = DRFQuotaGate(hungry_grace_s=2.0)
        sched_client = HTTPAPIClient(url, watch_batch_s=0.002,
                                     watch_kinds=("node", "pod", "pv",
                                                  "pvc", "quota"),
                                     wire=wire)
        closers.append(sched_client)
        sched = Scheduler(sched_client, ds, bind_async=True, quota=gate)
        sched.start()

        life_client = HTTPAPIClient(url, wire=wire)
        closers.append(life_client)
        lifecycle = NodeLifecycle(life_client, stale_after_s=0.6,
                                  lost_after_s=2.0)
        lifecycle.start(interval_s=0.1)

        # lease renewals go THROUGH a proxy replica when fronted: the
        # forwarded path must keep them on the exempt system band at
        # both hops, or the flood scenario's zero-lease-loss invariant
        # breaks exactly here
        lease_client = HTTPAPIClient(shard_url(1), wire=wire)
        closers.append(lease_client)
        elector = Elector(lease_client.acquire_lease, "flood-lease",
                          "survivor", ttl_s=0.6)
        elector.start(interval_s=0.15)

        # bound/deleted completion straight off the admin watch stream
        bound_seen: dict = {}
        deleted_seen: dict = {}

        def track(kind, event, obj):
            if kind != "pod":
                return
            pname = obj["metadata"]["name"]
            if event in ("added", "modified") and \
                    (obj.get("spec") or {}).get("nodeName"):
                ev = bound_seen.get(pname)
                if ev is not None:
                    ev.set()
            elif event == "deleted":
                ev = deleted_seen.get(pname)
                if ev is not None:
                    ev.set()

        admin.add_watcher(track)

        tenant_names = [f"tenant-{i}" for i in range(tenants)]

        def churn(idx, tenant, phase, latencies, errors):
            """One well-behaved tenant: sequential create -> bound ->
            delete churn, honoring any front-door retry-after like a
            good citizen. Latency is the full user-visible
            create->bound span, throttle waits included. Fronted,
            each tenant talks to its shard's proxy replica — writes
            forward upstream, watches and reads are the replica's."""
            client = HTTPAPIClient(shard_url(idx), wire=wire)
            try:
                for k in range(churn_pods):
                    pname = f"{tenant}-{phase}-{k}"
                    bound_seen[pname] = threading.Event()
                    t0 = time.perf_counter()
                    for _attempt in range(200):
                        try:
                            client.create_pod(
                                tenant_pod(pname, tenant))
                            break
                        except TooManyRequests as e:
                            time.sleep(max(0.01, e.retry_after_s))
                    else:
                        errors.append(f"{pname}: create never admitted")
                        return
                    if not bound_seen[pname].wait(deadline_s):
                        errors.append(f"{pname}: never bound")
                        return
                    latencies.append(time.perf_counter() - t0)
                    deleted_seen[pname] = threading.Event()
                    for _attempt in range(200):
                        try:
                            client.delete_pod(pname)
                            break
                        except TooManyRequests as e:
                            # the DELETE's own idempotent retries
                            # exhausted under flood: keep being a good
                            # citizen rather than dying silently
                            time.sleep(max(0.01, e.retry_after_s))
                    else:
                        errors.append(f"{pname}: delete never admitted")
                        return
                    deleted_seen[pname].wait(10.0)
            finally:
                client.close()

        def run_phase(phase):
            latencies: list = []
            errors: list = []
            threads = [threading.Thread(target=churn,
                                        args=(i, t, phase, latencies,
                                              errors),
                                        daemon=True)
                       for i, t in enumerate(tenant_names)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=deadline_s * 2)
            hung = sum(1 for t in threads if t.is_alive())
            if hung:
                # a join timeout is not success: a wedged churn thread
                # would otherwise slip past the placement invariants
                # with partial latency data
                errors.append(f"{hung} churn thread(s) still running "
                              f"after {deadline_s * 2:.0f}s")
            if errors:
                raise RuntimeError(
                    f"{phase} churn failed: {errors[:4]} "
                    f"(faults so far: front_door={_apf_summary()})")
            return latencies

        def p99(lat):
            s = sorted(lat)
            return s[int(0.99 * (len(s) - 1))] * 1e3

        # apiserver-side request rate per phase: the proxy variant's
        # headline invariant is that this stays FLAT under flood (the
        # read flood is absorbed at a replica's mirror)
        apiserver_reqs = metrics.API_REQUESTS.labels("apiserver")

        quiet_reqs0 = apiserver_reqs.value
        quiet_t0 = time.perf_counter()
        quiet_lat = run_phase("quiet")
        quiet_req_rate = (apiserver_reqs.value - quiet_reqs0) / \
            max(time.perf_counter() - quiet_t0, 1e-9)

        lease_transitions_before = elector.transitions
        node_lost_before = metrics.NODE_LOST.value
        evicted_before = lifecycle.evicted_total
        quota_parked_before = metrics.QUOTA_PARKED.value

        # fronted: the abuser aims a READ flood at ONE replica (its
        # shard) — reads are served from that replica's mirror, so the
        # apiserver must not see the flood at all. Direct: the original
        # create flood against the apiserver's own front door.
        flood = TenantFlood(
            lambda: HTTPAPIClient(shard_url(0), wire=wire),
            tenant="abuser", threads=flood_threads,
            pace_s=flood_pace_s,
            mode="read" if replicas else "mutate").start()
        flood_reqs0 = apiserver_reqs.value
        flood_t0 = time.perf_counter()
        try:
            flood_lat = run_phase("flood")
        finally:
            flood_counts = flood.stop()
        flood_req_rate = (apiserver_reqs.value - flood_reqs0) / \
            max(time.perf_counter() - flood_t0, 1e-9)

        quiet_p99 = p99(quiet_lat)
        flood_p99 = p99(flood_lat)
        ratio = flood_p99 / quiet_p99 if quiet_p99 > 0 else 0.0

        # the abuser's bound chips must sit at/below its DRF fair share
        # (tenants+1 actors; +1 pod slack for an admit that raced the
        # final release). Capacity is derived from the nodes actually
        # advertised, never assumed from the topology constants above.
        from kubegpu_tpu.cluster.apf import pod_chip_request
        from kubegpu_tpu.scheduler.quota import node_resource_totals

        abuser_bound = sum(
            pod_chip_request(p) for p in admin.list_pods(bound=True)
            if ((p["metadata"].get("labels") or {})
                .get("kgtpu.io/tenant")) == "abuser")
        total_chips = sum(node_resource_totals(n)["chips"]
                          for n in admin.list_nodes())
        fair_chips = total_chips / (tenants + 1)

        front_door = _apf_summary()
        failures = []
        if ratio > p99_ratio_limit:
            failures.append(
                f"well-behaved p99 degraded {ratio:.2f}x under flood "
                f"({quiet_p99:.1f} -> {flood_p99:.1f} ms, limit "
                f"{p99_ratio_limit}x)")
        if elector.transitions != lease_transitions_before:
            failures.append(
                f"lease lost during flood ({elector.transitions - lease_transitions_before} transition(s))")
        if metrics.NODE_LOST.value != node_lost_before or \
                lifecycle.evicted_total != evicted_before:
            failures.append("heartbeat-driven node loss/eviction "
                            "during flood")
        if front_door["apf_rejects_total"].get(BAND_SYSTEM, 0):
            failures.append("system band traffic was rejected")
        if sched_client.relist_count != 0:
            failures.append(
                f"scheduler watch lost its resume window under flood "
                f"({sched_client.relist_count} relist(s))")
        quota_parked_during = \
            metrics.QUOTA_PARKED.value - quota_parked_before
        if replicas:
            # read-flood mechanics: the DRF gate never sees the abuser
            # (nothing is created), so the invariant moves to the hop —
            # the apiserver's request rate must stay flat while the
            # replica absorbs the flood from its mirror
            if flood_counts["accepted"] + flood_counts["rejected"] == 0:
                failures.append("read flood never engaged the proxy "
                                "tier")
            rate_ratio = flood_req_rate / quiet_req_rate \
                if quiet_req_rate > 0 else 0.0
            if rate_ratio > api_rate_ratio_limit:
                failures.append(
                    f"apiserver request rate rose {rate_ratio:.2f}x "
                    f"under flood ({quiet_req_rate:.0f} -> "
                    f"{flood_req_rate:.0f} req/s, limit "
                    f"{api_rate_ratio_limit}x): the flood leaked "
                    f"through the proxy tier")
        else:
            if gate.parked_count() == 0 and quota_parked_during == 0:
                # the DELTA, not the process-global counter: earlier
                # runs in the same process must not mask a no-op gate
                failures.append(
                    "DRF gate never engaged against the flood")
            if abuser_bound > fair_chips + 1:
                failures.append(
                    f"abuser bound {abuser_bound} chips, over its fair "
                    f"share of {fair_chips:.1f}")
        if failures:
            raise RuntimeError("tenant-flood invariants violated: "
                               + "; ".join(failures))
        out = {"wellbehaved_quiet_p99_ms": round(quiet_p99, 2),
               "wellbehaved_flood_p99_ms": round(flood_p99, 2),
               "p99_ratio": round(ratio, 2),
               "flood": flood_counts,
               "abuser_bound_chips": abuser_bound,
               "abuser_fair_chips": round(fair_chips, 1),
               "quota_parked": quota_parked_during,
               "front_door": front_door,
               "lease_transitions": elector.transitions,
               "watch_relists": sched_client.relist_count,
               "evictions": lifecycle.evicted_total,
               "apiserver_quiet_req_per_s": round(quiet_req_rate, 1),
               "apiserver_flood_req_per_s": round(flood_req_rate, 1)}
        if replicas:
            out["proxies"] = _proxy_summary(replicas)
        return out
    finally:
        if elector is not None:
            elector.stop()
        if lifecycle is not None:
            lifecycle.stop()
        for adv in advs:
            adv.stop()
        if sched is not None:
            sched.stop()
        for closer in closers:
            closer.close()
        admin.close()
        for replica in replicas:
            replica.stop()
        server.shutdown()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--hosts", type=int, default=4)
    parser.add_argument("--schedulers", type=int, default=1,
                        help="optimistic scheduler replicas over one API "
                             "server (shard leases + conflict commits)")
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument("--chaos", nargs="?", const="node-loss",
                        choices=("node-loss", "chip-kill"), default=None,
                        help="run a device-failure recovery scenario: "
                             "node-loss (the default when the flag is "
                             "bare — node agent killed mid-gang under "
                             "the seeded chaos transport) or chip-kill "
                             "(an allocated chip dies; the repair "
                             "controller checkpoints + migrates the "
                             "gang)")
    parser.add_argument("--chaos-ha", action="store_true",
                        help="run the HA scenario: scheduler-kill + "
                             "WAL-backed apiserver restart under 2 "
                             "replicas")
    parser.add_argument("--chaos-tenant-flood", action="store_true",
                        help="run the multi-tenant overload scenario: "
                             "one abusive tenant floods creates through "
                             "the priority-&-fairness front door while "
                             "well-behaved tenants churn; asserts p99 "
                             "isolation, zero lease losses, zero "
                             "heartbeat evictions")
    parser.add_argument("--proxies", type=int, default=0,
                        help="front the apiserver with N shared-nothing "
                             "watch-cache proxy replicas "
                             "(cluster/proxy.py), each with its own APF "
                             "front door; tenants shard across them. "
                             "With --chaos-tenant-flood the abuser "
                             "becomes a read flood against one replica "
                             "and the apiserver-side request rate is "
                             "asserted flat")
    parser.add_argument("--seed", type=int, default=0,
                        help="chaos transport seed")
    parser.add_argument("--wire", choices=("stream", "json"),
                        default="stream",
                        help="control-plane wire for the HTTP scenarios "
                             "(--chaos-ha): framed binary streams "
                             "(default) or JSON long-poll")
    parser.add_argument("--trace-out", default=None,
                        help="write the run's span ring as Chrome "
                             "trace-event JSON (open in Perfetto); "
                             "covers every in-process component — "
                             "scheduler replicas AND the apiserver")
    from kubegpu_tpu.cmd import common

    common.add_observability_flags(parser)
    args = parser.parse_args(argv)
    # sampler + metrics time-series cover the whole run (chaos scenarios
    # included). A chaos scenario's failed in-scenario assert is exactly
    # when the trace + profile matter most, so both writers run in a
    # finally — every exit path, not just the clean returns.
    stop_obs = common.start_observability(args)
    try:
        return _run_simulation(args)
    finally:
        if args.trace_out:
            import sys

            n = obs.write_trace(args.trace_out)
            # stderr: --json consumers parse stdout as one document
            print(f"trace: {n} spans -> {args.trace_out}",
                  file=sys.stderr, flush=True)
        stop_obs()


def _run_simulation(args) -> int:
    if args.chaos == "chip-kill":
        result = run_chip_kill_scenario(seed=args.seed)
        if args.json:
            print(json.dumps(result, indent=2))
        else:
            print(f"chip {result['victim']['chip']} on "
                  f"{result['victim']['node']} killed mid-gang; "
                  f"checkpointed + migrated in "
                  f"{result['recovery_ms']:.0f} ms "
                  f"({result['first_placement']} -> "
                  f"{result['final_placement']})")
        return 0

    if args.chaos:
        result = run_chaos_scenario(seed=args.seed)
        if args.json:
            print(json.dumps(result, indent=2))
        else:
            print(f"node {result['victim']} killed mid-gang; recovered in "
                  f"{result['recovery_ms']:.0f} ms "
                  f"({result['first_placement']} -> "
                  f"{result['final_placement']})")
        return 0

    if args.chaos_tenant_flood:
        result = run_tenant_flood_scenario(wire=args.wire,
                                           proxies=args.proxies)
        result["wire_protocol"] = args.wire
        if args.json:
            print(json.dumps(result, indent=2))
        elif args.proxies:
            print(f"tenant flood ({args.proxies} proxies): well-behaved "
                  f"p99 {result['wellbehaved_quiet_p99_ms']} -> "
                  f"{result['wellbehaved_flood_p99_ms']} ms "
                  f"({result['p99_ratio']}x) while the abuser's read "
                  f"flood ({result['flood']['accepted']} served / "
                  f"{result['flood']['rejected']} shed) was absorbed "
                  f"at the proxy tier — apiserver "
                  f"{result['apiserver_quiet_req_per_s']} -> "
                  f"{result['apiserver_flood_req_per_s']} req/s; "
                  f"0 lease losses, 0 evictions; proxies="
                  f"{result['proxies']}")
        else:
            print(f"tenant flood: well-behaved p99 "
                  f"{result['wellbehaved_quiet_p99_ms']} -> "
                  f"{result['wellbehaved_flood_p99_ms']} ms "
                  f"({result['p99_ratio']}x) while the abuser had "
                  f"{result['flood']['accepted']} creates admitted / "
                  f"{result['flood']['rejected']} shed, "
                  f"{result['quota_parked']} pods quota-parked, "
                  f"abuser bound {result['abuser_bound_chips']} of "
                  f"{result['abuser_fair_chips']} fair chips; "
                  f"0 lease losses, 0 evictions")
        return 0

    if args.chaos_ha:
        result = run_ha_chaos_scenario(wire=args.wire)
        result["wire_protocol"] = args.wire
        if args.json:
            print(json.dumps(result, indent=2))
        else:
            print(f"HA chaos: {result['placed']} pods placed exactly once "
                  f"across a scheduler kill + apiserver restart "
                  f"({result['ha']['sched_conflicts_total']} conflicts "
                  f"arbitrated, {result['watch_relists']} relists, "
                  f"{result['wal_recovered_records']} WAL records "
                  f"replayed)")
        return 0

    api = InMemoryAPIServer()
    hooks = {}
    origins = [(2 * (i % 2), 2 * (i // 2), 0) for i in range(args.hosts)]
    mesh_dims = (4, 2 * ((args.hosts + 1) // 2), 1)
    for i, origin in enumerate(origins):
        name = f"host{i}"
        api.create_node({"metadata": {"name": name,
                                      "labels": {"kubernetes.io/hostname":
                                                 name}},
                         "status": {"allocatable": {"cpu": "64", "pods": 100}}})
        mgr = DevicesManager()
        mgr.add_device(TPUDeviceManager(FakeTPUBackend(
            v5p_host_inventory(host_origin=origin, mesh_dims=mesh_dims))))
        mgr.start()
        DeviceAdvertiser(api, mgr, name).advertise_once()
        hooks[name] = TPURuntimeHook(api, mgr)

    # Pipelined binder, like the real binary: the data-plane summary
    # below then reports live bind pipeline numbers. With
    # --schedulers N, N optimistic replicas share the API server: each
    # owns a pod-name-hash shard under a lease (InMemoryAPIServer serves
    # the same lease surface as the HTTP transport), gangs route whole
    # by gang id, and conflicting commits are arbitrated server-side.
    from kubegpu_tpu.cluster.lease import ShardCoordinator

    n_sched = max(1, args.schedulers)
    scheds = []
    coords = []
    for shard in range(n_sched):
        ds = DevicesScheduler()
        ds.add_device(TPUScheduler())
        owns = None
        if n_sched > 1:
            coord = ShardCoordinator(api, shard, n_sched, f"sim-{shard}",
                                     ttl_s=5.0)
            coords.append(coord)
            owns = coord.owns
        s = Scheduler(api, ds, bind_async=True, shard_owned=owns,
                      name=f"sched-{shard}")
        if n_sched > 1:
            coords[shard].on_change = s.queue.move_all_to_active
            coords[shard].tick()
        scheds.append(s)
    sched = scheds[0]

    api.create_pod(make_pod("plain-2chip", 2))
    api.create_pod(make_pod("hbm-floored", 1, hbm=90 * 2**30))
    api.create_pod(make_pod("contig-4chip", 4,
                            pod_requests={RESOURCE_CONTIGUOUS: 1}))
    # volume-bound pod: the PV's node affinity pins it to host1 (which
    # the mixed pods leave a chip on), so placement is visibly steered
    # and the claim flips to Bound at schedule time — without stealing a
    # chip the gang needs
    pinned_host = f"host{min(1, args.hosts - 1)}"
    api.create_pvc({"metadata": {"name": "demo-claim"},
                    "spec": {"resources": {"requests": {"storage": "10Gi"}},
                             "storageClassName": ""}})
    api.create_pv({"metadata": {"name": "demo-vol"},
                   "spec": {"capacity": {"storage": "10Gi"},
                            "storageClassName": "",
                            "nodeAffinity": {"required": {
                                "nodeSelectorTerms": [{"matchExpressions": [
                                    {"key": "kubernetes.io/hostname",
                                     "operator": "In",
                                     "values": [pinned_host]}]}]}}}})
    vol_pod = make_pod("vol-1chip", 1)
    vol_pod["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": "demo-claim"}}]
    api.create_pod(vol_pod)
    gang_n = min(2, args.hosts)
    for i in range(gang_n):
        api.create_pod(make_pod(f"gang-{i}", 4,
                                pod_requests={RESOURCE_GANG: 1,
                                              RESOURCE_GANG_SIZE: gang_n}))
    if n_sched == 1:
        sched.run_until_idle()
    else:
        # round-robin the replicas' cycles until the cluster settles —
        # each drains only its owned shard; a replica observing another's
        # mid-flight assume simply loses that conflict and requeues
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            for c in coords:
                c.tick()
            for s in scheds:
                s.run_until_idle()
            if all((p.get("spec") or {}).get("nodeName")
                   for p in api.list_pods()):
                break
            time.sleep(0.02)

    rows = []
    for pod in api.list_pods():
        name = pod["metadata"]["name"]
        node = pod.get("spec", {}).get("nodeName")
        env = {}
        if node:
            cfg = hooks[node].create_container(name, "main", {})
            env = {e["key"]: e["value"] for e in cfg.get("envs", [])}
        row = {"pod": name, "node": node or "<pending>",
               "chips": env.get("TPU_CHIP_IDS", ""),
               "bounds": env.get("TPU_PROCESS_BOUNDS", "")}
        if name == "vol-1chip":
            row["volume"] = api.get_pvc("demo-claim")["spec"] \
                .get("volumeName", "<unbound>")
        rows.append(row)

    fit_cache = _fit_cache_summary()
    data_plane = _data_plane_summary()
    batch = _batch_summary()
    doc = {"placements": rows, "fit_cache": fit_cache,
           "batch": batch, "data_plane": data_plane}
    if n_sched > 1:
        doc["ha"] = {"schedulers": n_sched, **_ha_summary()}
    if metrics.SERVE_TTFT_MS.n:
        doc["serving"] = _serving_summary()
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        width = max(len(r["pod"]) for r in rows) + 2
        print(f"{'POD':<{width}}{'NODE':<10}{'CHIPS':<28}{'BOUNDS':<8}VOLUME")
        for r in rows:
            print(f"{r['pod']:<{width}}{r['node']:<10}{r['chips']:<28}"
                  f"{r['bounds']:<8}{r.get('volume', '')}")
        print(f"fit cache: {fit_cache['hits']} hits / "
              f"{fit_cache['misses']} misses / "
              f"{fit_cache['invalidations']} invalidations")
        print(f"batch: {batch['cycles']} cycles, "
              f"{batch['pods_per_cycle_mean']} pods/cycle, "
              f"{batch['classes_per_cycle_mean']} classes/cycle, "
              f"{batch['throughput_pods_per_s']} pods/s bound")
        print(f"data plane: {data_plane['bind_count']} binds "
              f"(p50 {data_plane['bind_p50_ms']} ms, "
              f"{data_plane['bind_inflight']} in flight); last watch "
              f"batch {data_plane['watch_batch_size']}, "
              f"{data_plane['watch_coalesced_total']} events coalesced")
        if n_sched > 1:
            ha = doc["ha"]
            print(f"ha: {ha['schedulers']} replicas, "
                  f"{ha['sched_conflicts_total']} conflicts arbitrated, "
                  f"{ha['lease_transitions_total']} lease transitions")
    for s in scheds:
        s.stop()
    for coord in coords:
        coord.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

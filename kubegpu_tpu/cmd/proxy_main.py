"""``kgtpu-proxy``: a watch-cache proxy replica fronting the apiserver.

Holds ONE upstream stream subscription and re-serves thousands of
downstream watchers from a local event window (cluster/proxy.py) —
resume stays seq-exact through the proxy because the sequence space is
the apiserver's own (WAL-continued), so clients migrate between a
replica and the apiserver without a relist. Reads are answered from the
mirrored store; writes forward upstream with typed errors intact. Run N
replicas (shared-nothing) and point each client shard at one.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubegpu_tpu.cmd import common


def main(argv=None) -> int:
    # same rationale as the apiserver binary: a busy encode thread must
    # not stall a reply for a whole default GIL window
    sys.setswitchinterval(0.0005)
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--upstream", required=True,
                        help="apiserver base URL this replica mirrors "
                             "(e.g. http://127.0.0.1:8070)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument("--name", default="proxy",
                        help="replica name: labels "
                             "proxy_downstream_watchers and the "
                             "upstream consumer thread")
    parser.add_argument("--wire", choices=("stream", "json"),
                        default="stream",
                        help="downstream wire: stream (default) also "
                             "serves the framed binary wire; json "
                             "refuses upgrades. The upstream leg "
                             "negotiates independently")
    parser.add_argument("--window", type=int, default=10000,
                        help="local event-window size (events); a "
                             "resume below it backfills from the "
                             "deeper upstream window")
    parser.add_argument("--apf", action="store_true",
                        help="per-replica priority-&-fairness front "
                             "door: an abusive tenant saturates only "
                             "this shard, system traffic stays exempt")
    common.add_observability_flags(parser)
    args = parser.parse_args(argv)

    stop_obs = common.start_observability(args)
    apf = None
    if args.apf:
        from kubegpu_tpu.cluster.apf import APFDispatcher

        apf = APFDispatcher()
    from kubegpu_tpu.cluster.proxy import WatchCacheProxy

    proxy = WatchCacheProxy(args.upstream, name=args.name,
                            host=args.host, port=args.port,
                            apf=apf, limit=args.window,
                            stream_wire=args.wire == "stream")
    print(f"proxy {args.name} listening at {proxy.url} "
          f"(upstream {args.upstream})"
          + (" (APF front door on)" if apf else ""), flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    proxy.stop()
    stop_obs()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""``kgtpu-train``: the workload a scheduled pod actually runs.

The "8-chip JAX job" end of the placement contract, as a binary: build a
mesh from the chips the runtime hook granted (``TPU_VISIBLE_CHIPS`` via
`spmd.mesh_from_env` — or every visible device standalone), stream
batches from token shards through the native data loader
(`native/dataloader.cpp`, Python fallback), and run the sharded train
step. Synthetic shards are generated on demand so the demo runs
anywhere.

    python -m kubegpu_tpu.cmd.train_demo --steps 4 --d-model 64
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


def main(argv=None) -> int:
    from kubegpu_tpu.workload.presets import preset_names

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", nargs="*", default=None,
                    help="token shard paths (default: generate synthetic)")
    ap.add_argument("--preset", default=None, choices=preset_names(),
                    help="model family (workload/presets.py); size flags "
                         "below override its dimensions")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    # size flags default to None so only EXPLICIT values override a
    # preset's dimensions (without --preset they fall back to the
    # defaults noted in each help string)
    ap.add_argument("--seq", type=int, default=None, help="default 128")
    ap.add_argument("--vocab", type=int, default=None, help="default 512")
    ap.add_argument("--d-model", type=int, default=None,
                    help="default 128 (d_ff follows at 4x)")
    ap.add_argument("--n-layers", type=int, default=None, help="default 2")
    ap.add_argument("--n-heads", type=int, default=None, help="default 4")
    ap.add_argument("--dp", type=int, default=None,
                    help="mesh data-parallel extent (with --sp/--tp; "
                         "default: auto-factor the visible devices)")
    ap.add_argument("--sp", type=int, default=None)
    ap.add_argument("--tp", type=int, default=None)
    ap.add_argument("--accum-steps", type=int, default=1,
                    help="gradient-accumulation microbatches per step")
    ap.add_argument("--lora-rank", type=int, default=0,
                    help="train rank-r LoRA adapters instead of the "
                         "full model (0 = full fine-tune)")
    ap.add_argument("--remat", default="none",
                    choices=["none", "dots", "full"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save train state here and resume from the "
                         "latest step on start (elastic restart)")
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--generate", type=int, default=0, metavar="N",
                    help="after training, decode N tokens from a prompt "
                         "drawn from the data stream")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy (default); >0 samples")
    ap.add_argument("--top-k", type=int, default=0,
                    help="sample from the k highest-logit tokens")
    ap.add_argument("--top-p", type=float, default=1.0,
                    help="nucleus sampling mass (0, 1]")
    args = ap.parse_args(argv)
    if args.steps < 1:
        ap.error("--steps must be >= 1")
    if args.checkpoint_every < 1:
        ap.error("--checkpoint-every must be >= 1")
    if args.lora_rank > 0 and args.accum_steps != 1:
        ap.error("--accum-steps is not supported with --lora-rank")

    import jax

    # honor an explicit platform choice even under a sitecustomize that
    # pins a TPU-tunnel plugin (same workaround as tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    import numpy as np

    from kubegpu_tpu.workload import spmd

    # Gang-scheduled pods join one jax.distributed process group before
    # ANY other jax call: the runtime hook injected the coordinator/rank
    # env alongside TPU_VISIBLE_CHIPS (no-op for single-process runs).
    multiproc = spmd.distributed_init_from_env()
    from kubegpu_tpu.workload.data import make_loader, write_token_shard
    from kubegpu_tpu.workload.model import TransformerConfig
    from kubegpu_tpu.workload.train import init_sharded, make_train_step

    explicit = {"remat": args.remat}
    if args.vocab is not None:
        explicit["vocab"] = args.vocab
    if args.d_model is not None:
        explicit["d_model"] = args.d_model
        explicit["d_ff"] = 4 * args.d_model
    if args.n_layers is not None:
        explicit["n_layers"] = args.n_layers
    if args.n_heads is not None:
        explicit["n_heads"] = args.n_heads
    if args.seq is not None:
        explicit["max_seq"] = args.seq
    if args.preset:
        from kubegpu_tpu.workload.presets import make_config

        cfg = make_config(args.preset, **explicit)
    else:
        cfg = TransformerConfig(**{
            **dict(vocab=512, d_model=128, n_heads=4, n_layers=2,
                   d_ff=512, max_seq=128),
            **explicit})
    seq_len = args.seq if args.seq is not None else cfg.max_seq

    paths = args.data
    tmp = None
    if not paths:
        tmp = tempfile.mkdtemp(prefix="kgtpu-tokens-")
        rng = np.random.default_rng(args.seed)
        paths = [write_token_shard(
            os.path.join(tmp, f"shard{i}.kgtd"),
            rng.integers(0, cfg.vocab, size=50_000, dtype=np.uint32))
            for i in range(2)]

    if args.dp or args.sp or args.tp:
        if not (args.dp and args.sp and args.tp):
            ap.error("--dp/--sp/--tp must be given together")
        want = args.dp * args.sp * args.tp
        if multiproc and want != len(jax.devices()):
            # a sub-mesh is fine single-process; across processes it
            # would strand whole ranks outside the mesh and crash the
            # first global array mid-run instead of failing here
            ap.error(f"--dp*--sp*--tp = {want} but the process group has "
                     f"{len(jax.devices())} devices")
        mesh = spmd.make_mesh(want, dp=args.dp, sp=args.sp, tp=args.tp)
    else:
        mesh = spmd.mesh_from_env()

    # Build (and thereby validate) the generator BEFORE training: a bad
    # flag combination must fail up front, not after the last step when
    # an uncheckpointed session's params would be lost.
    gen = None
    prompt_len = min(16, seq_len)
    if args.generate > 0 and multiproc:
        ap.error("--generate is single-process only (decode slices the "
                 "batch outside jit, which a cross-process array forbids)")
    if args.generate > 0:
        from kubegpu_tpu.workload.decode import make_generate

        gen = jax.jit(make_generate(cfg, mesh, temperature=args.temperature,
                                    top_k=args.top_k, top_p=args.top_p),
                      static_argnums=(2,))
        if prompt_len + args.generate > cfg.max_seq:
            ap.error(f"--generate {args.generate} + prompt {prompt_len} "
                     f"exceeds the model's max_seq {cfg.max_seq}")

    lora = None
    if args.lora_rank > 0:
        # parameter-efficient fine-tune: adapters train, base is frozen
        # (reproducible from --seed). init_optimizer=False skips the
        # O(model) Adam moments entirely — optimizer state stays
        # adapter-sized from the first allocation.
        from kubegpu_tpu.workload.lora import (init_lora, merge_lora,
                                               make_lora_train_step)

        params, _, optimizer = init_sharded(
            jax.random.PRNGKey(args.seed), cfg, mesh,
            init_optimizer=False)
        lora = init_lora(jax.random.PRNGKey(args.seed + 1), params,
                         rank=args.lora_rank)
        opt_state = optimizer.init(lora)
        lora_step = make_lora_train_step(cfg, mesh, args.lora_rank,
                                         optimizer)
    else:
        params, opt_state, optimizer = init_sharded(
            jax.random.PRNGKey(args.seed), cfg, mesh)
        step = make_train_step(cfg, mesh, optimizer,
                               accum_steps=args.accum_steps)

    # elastic restart: a killed pod's replacement resumes from the last
    # saved step — the workload-side analogue of the scheduler rebuilding
    # from annotations (docs/design.md failure model). In LoRA mode the
    # checkpoint carries the ADAPTERS (the base is reproducible from
    # --seed), so resumable fine-tune state stays adapter-sized.
    start_step = 0
    if args.checkpoint_dir:
        from kubegpu_tpu.workload.checkpoint import (restore_checkpoint,
                                                     save_checkpoint)

        train_state = {"params": lora if lora is not None else params,
                       "opt_state": opt_state}
        state, at = restore_checkpoint(args.checkpoint_dir, train_state)
        if state is not None:
            if lora is not None:
                lora = state["params"]
            else:
                params = state["params"]
            opt_state = state["opt_state"]
            start_step = at

    loader = make_loader(paths, args.batch, seq_len, seed=args.seed)
    loader_kind = type(loader).__name__

    losses = []
    t0 = time.perf_counter()
    try:
        # the loader stream is deterministic from (seed): fast-forward
        # past the batches the checkpointed steps already consumed, so a
        # resumed run CONTINUES the stream instead of re-training on them
        for _ in range(start_step):
            next(loader)
        for i in range(start_step, start_step + args.steps):
            # every process streams the SAME deterministic global batch;
            # global_batch shards it over the mesh's data axis (the only
            # correct multi-process feed; a plain asarray single-process)
            tokens = spmd.global_batch(mesh, np.asarray(next(loader)))
            if lora is not None:
                lora, opt_state, loss = lora_step(lora, opt_state, params,
                                                  tokens)
            else:
                params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(jax.device_get(loss)))
            if args.checkpoint_dir and (i + 1) % args.checkpoint_every == 0:
                save_checkpoint(
                    args.checkpoint_dir,
                    {"params": lora if lora is not None else params,
                     "opt_state": opt_state},
                    step=i + 1)
    finally:
        loader.close()
    wall = time.perf_counter() - t0

    out = {
        "loader": loader_kind,
        "devices": len(mesh.devices.flatten()),
        "resumed_from_step": start_step,
        "steps": args.steps,
        "first_loss": round(losses[0], 4),
        "last_loss": round(losses[-1], 4),
        # full-precision per-step losses: lets a gang run be checked
        # bit-for-bit against its single-process twin
        "losses_full": losses,
        "tokens_per_s": round(args.steps * args.batch * seq_len / wall, 1),
    }

    if gen is not None:
        # full batch (a dp-sharded mesh can't split batch 1); print row 0
        gen_params = params if lora is None else \
            merge_lora(params, lora, 1.0)  # matches the step's alpha/r = 1
        prompt = tokens[:, :prompt_len]
        toks = gen(gen_params, prompt, args.generate,
                   jax.random.PRNGKey(args.seed))
        out["generated"] = np.asarray(toks)[0].tolist()

    if multiproc:
        out["processes"] = jax.process_count()
        out["process_id"] = jax.process_index()
    # one JSON line per JOB: in a gang every rank computes identical
    # replicated losses, so rank 0 speaks for the group
    if jax.process_index() == 0 or not multiproc:
        print(json.dumps(out))
    return 0 if all(np.isfinite(losses)) else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""Process entry points (the reference's two binaries, plus transport).

- ``apiserver``  — serve the in-memory API server over HTTP
- ``node-agent`` — device discovery + advertiser loop (crishim's node half,
  reference `crishim/pkg/app/app.go`)
- ``scheduler``  — the scheduling engine with optional leader election
  (reference `kube-scheduler/cmd`)
- ``cri-hook``   — per-container config rewrite on stdin/stdout (OCI-hook
  style; reference `crishim/pkg/kubecri`)
- ``simulate``   — single-process cluster demo
"""

"""``kgtpu-apiserver``: serve the cluster state over HTTP."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubegpu_tpu.cluster.apiserver import InMemoryAPIServer
from kubegpu_tpu.cluster.httpapi import serve_api


def main(argv=None) -> int:
    # Latency-sensitive multi-threaded service: the default 5 ms GIL
    # switch interval lets one busy thread (a watch encode, a handler)
    # stall a request reply for whole milliseconds — measured ~0.5-1 ms
    # off the transport p50 per hop at 0.5 ms.
    sys.setswitchinterval(0.0005)
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8070)
    args = parser.parse_args(argv)

    api = InMemoryAPIServer()
    server, url = serve_api(api, args.host, args.port)
    print(f"apiserver listening at {url}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

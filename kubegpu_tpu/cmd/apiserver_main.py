"""``kgtpu-apiserver``: serve the cluster state over HTTP.

With ``--wal-dir`` the server is durable: every watch event is appended
to a checksummed write-ahead log before delivery, the object state is
snapshotted + the log compacted every ``--wal-snapshot-every`` events,
and a restart replays snapshot + log — watch clients resume seq-exact
instead of being stranded (see cluster/wal.py)."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubegpu_tpu.cluster.apiserver import InMemoryAPIServer
from kubegpu_tpu.cluster.httpapi import serve_api
from kubegpu_tpu.cmd import common


def main(argv=None) -> int:
    # Latency-sensitive multi-threaded service: the default 5 ms GIL
    # switch interval lets one busy thread (a watch encode, a handler)
    # stall a request reply for whole milliseconds — measured ~0.5-1 ms
    # off the transport p50 per hop at 0.5 ms.
    sys.setswitchinterval(0.0005)
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8070)
    parser.add_argument("--wire", choices=("stream", "json"),
                        default="stream",
                        help="stream (default) also serves the framed "
                             "binary wire behind an Upgrade handshake; "
                             "json refuses upgrades, so every client "
                             "negotiates down to JSON long-poll HTTP")
    parser.add_argument("--wal-dir", default=None,
                        help="directory for the write-ahead log + "
                             "snapshot; restart recovers state and the "
                             "watch sequence space from it")
    parser.add_argument("--wal-no-fsync", action="store_true",
                        help="skip fsync per append (durable across "
                             "process crashes, not power loss)")
    parser.add_argument("--wal-snapshot-every", type=int, default=4096,
                        help="events between snapshot+compaction passes")
    parser.add_argument("--apf", action="store_true",
                        help="enable the priority-&-fairness front "
                             "door: per-flow shuffle-sharded fair "
                             "queuing with bounded concurrency on both "
                             "wires; system traffic (heartbeats, "
                             "leases, watch) is exempt and shed work "
                             "gets a typed 429/REJECT with retry-after")
    common.add_observability_flags(parser)
    args = parser.parse_args(argv)

    # profiler + metrics time-series before any server object exists, so
    # the lock probe wraps the event log / WAL / fan-out locks
    stop_obs = common.start_observability(args)
    api = InMemoryAPIServer()
    wal = None
    if args.wal_dir:
        from kubegpu_tpu.cluster.wal import WriteAheadLog

        wal = WriteAheadLog(args.wal_dir, fsync=not args.wal_no_fsync,
                            snapshot_every=args.wal_snapshot_every)
    apf = None
    if args.apf:
        from kubegpu_tpu.cluster.apf import APFDispatcher

        apf = APFDispatcher()
    server, url = serve_api(api, args.host, args.port, wal=wal,
                            stream_wire=args.wire == "stream", apf=apf)
    print(f"apiserver listening at {url} (wire: {args.wire}+json)"
          + (f" (WAL at {args.wal_dir})" if wal else "")
          + (" (APF front door on)" if apf else ""), flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.shutdown()
    server.server_close()
    if wal is not None:
        wal.close()
    stop_obs()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

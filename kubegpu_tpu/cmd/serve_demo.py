"""Serving demo binary: continuous-batching decode over synthetic requests.

The serving counterpart of `cmd/train_demo.py`: builds a model (fresh
from --seed, or restored from a train_demo --checkpoint-dir), submits a
stream of synthetic requests with mixed prompt lengths, drives the
slot-based `DecodeServer`, and prints one JSON line of stats. With
--speculative, the same requests run through speculative decoding with a
smaller auto-built draft model instead — greedy, or sampled when
--temperature is set (with --top-k/--top-p both distributions are
truncated and renormalized; the acceptance rule stays exact).

Examples:
    python -m kubegpu_tpu.cmd.serve_demo --requests 8 --slots 4
    python -m kubegpu_tpu.cmd.serve_demo --temperature 0.8 --top-p 0.9
    python -m kubegpu_tpu.cmd.serve_demo --speculative --draft-layers 1
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--seq", type=int, default=256, help="model max_seq")
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="restore params saved by train_demo (full "
                         "fine-tune checkpoints only)")
    ap.add_argument("--speculative", action="store_true",
                    help="speculative decoding with a draft model "
                         "(greedy, or sampled when --temperature is set)")
    ap.add_argument("--spec-server", action="store_true",
                    help="speculative mode INSIDE the continuous-batching "
                         "server: per-slot draft proposals, one batched "
                         "verify")
    ap.add_argument("--draft-layers", type=int, default=1)
    ap.add_argument("--lookahead", type=int, default=4,
                    help="draft tokens per speculative round (k)")
    ap.add_argument("--prefix-cache", type=int, default=0, metavar="N",
                    help="retain K/V of the last N served prompts; "
                         "requests extending one prefill only the "
                         "remainder")
    args = ap.parse_args(argv)
    if args.requests < 1:
        ap.error("--requests must be >= 1")
    if args.prefix_cache < 0:
        ap.error("--prefix-cache must be >= 0")
    if args.prefix_cache and args.speculative:
        ap.error("--prefix-cache applies to the server modes only "
                 "(plain or --spec-server); --speculative is the "
                 "single-stream path with no admission cache")

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    import numpy as np

    from kubegpu_tpu.workload import spmd
    from kubegpu_tpu.workload.model import TransformerConfig, init_params

    # serving is a gang workload like training: a scheduled pod-set joins
    # one jax.distributed group from the hook-injected contract (no-op
    # single-process), then serves over a model-parallel mesh. The batch
    # stays replicated (dp=1): every rank drives the same host loop and
    # the decode outputs stay fully addressable on each process.
    multiproc = spmd.distributed_init_from_env()
    ndev = len(jax.devices())
    mesh = spmd.make_mesh(ndev, dp=1, sp=1, tp=ndev) if ndev > 1 else None

    cfg = TransformerConfig(vocab=args.vocab, d_model=args.d_model,
                            n_heads=args.n_heads, n_layers=args.n_layers,
                            d_ff=4 * args.d_model, max_seq=args.seq)
    if mesh is not None:
        # initialize DIRECTLY sharded (train.py's init pattern): a model
        # sized to need the mesh must never be materialized on one
        # device first, and small runs skip a full-model reshuffle
        from kubegpu_tpu.workload.train import init_sharded

        params, _, _ = init_sharded(jax.random.PRNGKey(args.seed), cfg,
                                    mesh, init_optimizer=False)
    else:
        params = init_params(jax.random.PRNGKey(args.seed), cfg)
    restored_step = None
    if args.checkpoint_dir:
        from kubegpu_tpu.workload.checkpoint import restore_checkpoint
        from kubegpu_tpu.workload.train import default_optimizer

        # train_demo saves {params, opt_state}; the restore template must
        # match that structure leaf-for-leaf or every step reads as
        # corrupt. eval_shape builds the optimizer-state skeleton without
        # materializing the O(model) Adam moments we're about to discard.
        opt_template = jax.eval_shape(default_optimizer().init, params)
        state, at = restore_checkpoint(
            args.checkpoint_dir,
            {"params": params, "opt_state": opt_template})
        ok = state is not None
        if multiproc:
            # EVERY rank must agree on restore success before any
            # collective: one rank exiting at ap.error while its peers
            # enter the first sharded op would hang the survivors until
            # the heartbeat/supervisor timeout
            from jax.experimental import multihost_utils

            ok = bool(multihost_utils.process_allgather(
                np.array([ok])).all())
        if not ok:
            ap.error(f"no readable checkpoint in {args.checkpoint_dir} "
                     "(serve_demo restores full fine-tune checkpoints "
                     "saved by train_demo; in a gang, every rank needs "
                     "the checkpoint readable)")
        params = state["params"]
        restored_step = at
        del state  # drop the restored Adam moments before serving

    def place(tree, tree_cfg):
        """Lay weights out on the serving mesh (fresh OR restored params
        land committed to one device otherwise, which conflicts with the
        forward's sharding constraints)."""
        if mesh is None:
            return tree
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(tree, jax.tree.map(
            lambda s: NamedSharding(mesh, s), spmd.param_pspecs(tree_cfg),
            is_leaf=lambda x: isinstance(x, PartitionSpec)))

    params = place(params, cfg)

    rng = np.random.default_rng(args.seed)
    prompts = [[int(t) for t in rng.integers(1, cfg.vocab,
                                             int(rng.integers(4, 24)))]
               for _ in range(args.requests)]

    draft_cfg = draft = None
    if args.speculative or args.spec_server:
        draft_cfg = TransformerConfig(
            vocab=args.vocab, d_model=max(32, args.d_model // 4),
            n_heads=args.n_heads, n_layers=args.draft_layers,
            d_ff=args.d_model, max_seq=args.seq)
        if mesh is not None:
            from kubegpu_tpu.workload.train import init_sharded

            draft, _, _ = init_sharded(jax.random.PRNGKey(args.seed + 1),
                                       draft_cfg, mesh,
                                       init_optimizer=False)
        else:
            draft = init_params(jax.random.PRNGKey(args.seed + 1),
                                draft_cfg)

    t0 = time.perf_counter()
    if args.speculative:
        from kubegpu_tpu.workload.speculative import (
            make_speculative_generate)

        gen = make_speculative_generate(cfg, draft_cfg, k=args.lookahead,
                                        mesh=mesh,
                                        temperature=args.temperature,
                                        top_k=args.top_k, top_p=args.top_p)
        outs, calls = [], 0
        for i, p in enumerate(prompts):
            out, c = gen(params, draft, p, args.max_new,
                         jax.random.PRNGKey(args.seed + 100 + i))
            outs.append(out)
            calls += c
        stats = {"mode": "speculative", "target_calls": calls,
                 "tokens": sum(len(o) for o in outs)}
    else:
        from kubegpu_tpu.workload.serve import DecodeServer

        srv = DecodeServer(cfg, params, slots=args.slots, mesh=mesh,
                           temperature=args.temperature, top_k=args.top_k,
                           top_p=args.top_p,
                           rng=jax.random.PRNGKey(args.seed),
                           draft_params=draft, draft_cfg=draft_cfg,
                           lookahead=args.lookahead,
                           prefix_cache_size=args.prefix_cache)
        rids = [srv.submit(p, max_new=args.max_new) for p in prompts]
        srv.run()
        outs = [srv.result(r) for r in rids]
        stats = {"mode": "spec-serve" if args.spec_server else "serve",
                 "slots": args.slots,
                 # which data plane served: the fused on-device chunk
                 # (default) or the per-token oracle (KGTPU_FUSED_SERVE=0)
                 "data_plane": "fused" if srv.fused else "hostloop",
                 "chunk": srv.chunk,
                 "tokens": sum(len(o) for o in outs)}
        if args.prefix_cache:
            stats["prefix_hits"] = srv.prefix_hits
            stats["prefix_misses"] = srv.prefix_misses
        from kubegpu_tpu import metrics as _metrics

        # per-request latency from the serving histograms (a fresh
        # process, so the samples are exactly this run's)
        if _metrics.SERVE_TTFT_MS.n:
            stats["ttft_p50_ms"] = round(
                _metrics.SERVE_TTFT_MS.percentile(0.5), 3)
            stats["itl_p50_ms"] = round(
                _metrics.SERVE_ITL_MS.percentile(0.5), 3)
    wall = time.perf_counter() - t0

    if restored_step is not None:
        stats["restored_step"] = restored_step
    if multiproc:
        stats["processes"] = jax.process_count()
    stats.update({
        "requests": args.requests,
        "wall_s": round(wall, 2),
        "tokens_per_s": round(stats["tokens"] / wall, 1),
        "first_output": outs[0][:8],
    })
    # one JSON line per JOB: in a gang every rank serves the identical
    # replicated batch, so rank 0 speaks for the group
    if jax.process_index() == 0 or not multiproc:
        print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

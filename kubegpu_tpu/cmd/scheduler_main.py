"""``kgtpu-scheduler``: the scheduling engine binary.

Reference: `kube-scheduler/cmd/scheduler.go` + `cmd/app/server.go` —
componentconfig-style ``--config``, healthz/metrics servers, and
lease-based leader election for HA (`server.go:396-403,437-461`): replicas
contend for one lease; only the holder schedules, and a lost lease demotes
the replica back to standby.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
import time

from kubegpu_tpu.cluster.httpapi import HTTPAPIClient
from kubegpu_tpu.cmd import common
from kubegpu_tpu.scheduler.core import Scheduler
from kubegpu_tpu.scheduler.registry import DevicesScheduler
from kubegpu_tpu.scheduler.tpu_scheduler import TPUScheduler

LEASE_NAME = "kgtpu-scheduler"


def build_scheduler(client, args, config: dict | None = None) -> Scheduler:
    from kubegpu_tpu.scheduler.extender import load_extenders
    from kubegpu_tpu.scheduler.factory import algorithm_from_policy

    config = config or {}
    ds = DevicesScheduler()
    ds.add_device(TPUScheduler())
    if getattr(args, "scheduler_plugins_dir", None):
        # the reference's /schedulerplugins seam (`cmd/scheduler.go:50-59`),
        # as a flag instead of a hardcoded path
        ds.add_devices_from_plugins(args.scheduler_plugins_dir)
    # A Policy document (`kube-scheduler/pkg/api/types.go`) recomposes the
    # predicate/priority set by name; inline under "policy" or in its own
    # file via "policyFile". Extenders declared inside the policy merge
    # with top-level ones (upstream puts them in the policy).
    policy = config.get("policy")
    if policy is None and config.get("policyFile"):
        policy = common.load_config(config["policyFile"])
    if policy:
        algorithm = algorithm_from_policy(policy)
    elif config.get("algorithmProvider"):
        from kubegpu_tpu.scheduler.factory import algorithm_provider

        algorithm = algorithm_provider(config["algorithmProvider"])
    else:
        algorithm = None
    extenders = load_extenders(config)
    if policy and policy.get("extenders"):
        extenders += load_extenders({"extenders": policy["extenders"]})
    sched = Scheduler(client, ds, bind_async=bool(args.bind_async),
                      parallelism=args.parallelism,
                      extenders=extenders,
                      priority_weights=config.get("priorityWeights"),
                      algorithm=algorithm)
    sched.preemption_enabled = not args.disable_preemption
    return sched


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--api", default="http://127.0.0.1:8070")
    parser.add_argument("--parallelism", type=int, default=16)
    parser.add_argument("--bind-async", action="store_true")
    parser.add_argument("--disable-preemption", action="store_true")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--lease-ttl", type=float, default=15.0)
    parser.add_argument("--healthz-port", type=int, default=0)
    parser.add_argument("--scheduler-plugins-dir", default=None,
                        help="load extra device-scheduler plugins (*.py "
                             "exporting create_device_scheduler_plugin)")
    parser.add_argument("--config", default=None,
                        help="JSON/YAML file; explicit flags win")
    args = parser.parse_args(argv)
    config = common.load_config(args.config)
    common.merge_flags(args, config, ["api", "parallelism", "lease_ttl"])

    client = HTTPAPIClient(args.api)
    holder = f"{os.uname().nodename}-{os.getpid()}"
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    sched: Scheduler | None = None
    common.serve_health(args.healthz_port,
                        extra_status=lambda: True)

    if not args.leader_elect:
        sched = build_scheduler(client, args, config)
        sched.start()
        print(f"scheduler running against {args.api}", flush=True)
        stop.wait()
        sched.stop()
        return 0

    # Leader election: acquire -> run; renew at ttl/3; demote on loss.
    print(f"scheduler candidate {holder} (leader election on)", flush=True)
    leading = False
    while not stop.is_set():
        acquired = client.acquire_lease(LEASE_NAME, holder, args.lease_ttl)
        if acquired and not leading:
            sched = build_scheduler(client, args, config)
            sched.start()
            leading = True
            print(f"{holder} became leader", flush=True)
        elif not acquired and leading:
            sched.stop()
            sched = None
            leading = False
            print(f"{holder} lost the lease, standing by", flush=True)
        stop.wait(args.lease_ttl / 3.0)
    if sched is not None:
        sched.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

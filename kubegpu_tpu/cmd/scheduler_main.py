"""``kgtpu-scheduler``: the scheduling engine binary.

Reference: `kube-scheduler/cmd/scheduler.go` + `cmd/app/server.go` —
componentconfig-style ``--config``, healthz/metrics servers, and
lease-based HA (`server.go:396-403,437-461`) in two shapes:

``--leader-elect``
    Active/standby: replicas contend for ONE lease; only the holder
    schedules, and a lost lease demotes the replica back to standby.

``--replicas N --shard I``
    Active/active (Omega-style): every replica schedules, each owning
    one shard of the queue by pod-name hash and holding that shard's
    lease. A replica also steals the work of any shard whose lease is
    vacant (its owner died), and stands down when the owner's renewals
    resume. Commit safety does NOT depend on the leases — the API
    server's optimistic-concurrency arbiter refuses conflicting binds —
    so a brief double-ownership during handoff only costs a requeue.

The NodeLifecycle controller is singleton-ELECTED (its own lease):
exactly one replica runs evictions at a time, regardless of which
scheduling mode is active.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading

from kubegpu_tpu.cluster.httpapi import HTTPAPIClient
from kubegpu_tpu.cluster.lease import (LIFECYCLE_LEASE, REPAIR_LEASE,
                                       Elector, ShardCoordinator)
from kubegpu_tpu.cmd import common
from kubegpu_tpu.scheduler.core import Scheduler
from kubegpu_tpu.scheduler.registry import DevicesScheduler
from kubegpu_tpu.scheduler.tpu_scheduler import TPUScheduler

LEASE_NAME = "kgtpu-scheduler"


def build_scheduler(client, args, config: dict | None = None,
                    shard_owned=None, name: str | None = None) -> Scheduler:
    from kubegpu_tpu.scheduler.extender import load_extenders
    from kubegpu_tpu.scheduler.factory import algorithm_from_policy

    config = config or {}
    ds = DevicesScheduler()
    ds.add_device(TPUScheduler())
    if getattr(args, "scheduler_plugins_dir", None):
        # the reference's /schedulerplugins seam (`cmd/scheduler.go:50-59`),
        # as a flag instead of a hardcoded path
        ds.add_devices_from_plugins(args.scheduler_plugins_dir)
    # A Policy document (`kube-scheduler/pkg/api/types.go`) recomposes the
    # predicate/priority set by name; inline under "policy" or in its own
    # file via "policyFile". Extenders declared inside the policy merge
    # with top-level ones (upstream puts them in the policy).
    policy = config.get("policy")
    if policy is None and config.get("policyFile"):
        policy = common.load_config(config["policyFile"])
    if policy:
        algorithm = algorithm_from_policy(policy)
    elif config.get("algorithmProvider"):
        from kubegpu_tpu.scheduler.factory import algorithm_provider

        algorithm = algorithm_provider(config["algorithmProvider"])
    else:
        algorithm = None
    extenders = load_extenders(config)
    if policy and policy.get("extenders"):
        extenders += load_extenders({"extenders": policy["extenders"]})
    quota = None
    if getattr(args, "tenant_quota", False):
        from kubegpu_tpu.scheduler.quota import DRFQuotaGate

        # per-tenant fair-share weights ride the config file:
        # {"tenantWeights": {"acme": 2.0, ...}}
        quota = DRFQuotaGate(weights=config.get("tenantWeights"))
    sched = Scheduler(client, ds, bind_async=bool(args.bind_async),
                      parallelism=args.parallelism,
                      extenders=extenders,
                      priority_weights=config.get("priorityWeights"),
                      algorithm=algorithm,
                      bind_workers=getattr(args, "bind_workers", 4),
                      shard_owned=shard_owned, name=name, quota=quota)
    sched.preemption_enabled = not args.disable_preemption
    return sched


def start_lifecycle_elector(client, args, holder: str) -> Elector | None:
    """Node liveness controller, gated on --node-grace-s and singleton-
    elected on its own lease: exactly one replica runs evictions (two
    controllers double-evicting would race the requeues), failover is
    automatic when the holder dies, and election is independent of which
    scheduling mode (leader-elect / sharded / solo) is active."""
    if not args.node_grace_s or args.node_grace_s <= 0:
        return None
    from kubegpu_tpu.scheduler.lifecycle import NodeLifecycle

    stale = args.node_stale_s if args.node_stale_s > 0 \
        else args.node_grace_s / 3.0
    controller = NodeLifecycle(client, stale_after_s=stale,
                               lost_after_s=args.node_grace_s)
    elector = Elector(client.acquire_lease, LIFECYCLE_LEASE, holder,
                      args.lease_ttl, on_acquire=controller.start,
                      on_lose=controller.stop)
    elector.start()
    return elector


def start_repair_elector(client, args, holder: str) -> Elector | None:
    """Device-fault repair controller, gated on --repair and singleton-
    elected on its own lease (same shape as the lifecycle elector): two
    controllers planning the same gang migration would double-evict."""
    if not getattr(args, "repair", False):
        return None
    from kubegpu_tpu.scheduler.repair import RepairController

    controller = RepairController(client)
    elector = Elector(client.acquire_lease, REPAIR_LEASE, holder,
                      args.lease_ttl, on_acquire=controller.start,
                      on_lose=controller.stop)
    elector.start()
    return elector


def main(argv=None) -> int:
    # Latency-sensitive control loop sharing its process with watch,
    # binder, and fit-pool threads: the default 5 ms GIL switch interval
    # lets any one of them stall the cycle for whole milliseconds.
    import sys

    sys.setswitchinterval(0.0005)
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--api", default="http://127.0.0.1:8070")
    parser.add_argument("--wire", choices=("stream", "json"),
                        default="stream",
                        help="control-plane wire: framed binary streams "
                             "with server-pushed watch deltas (default) "
                             "or JSON long-poll HTTP; stream negotiates "
                             "down to json against an older apiserver")
    parser.add_argument("--parallelism", type=int, default=16)
    parser.add_argument("--bind-async", action="store_true",
                        help="pipelined binder: the scheduling cycle "
                             "stops at assume and a bounded worker pool "
                             "overlaps the bind round trips")
    parser.add_argument("--bind-workers", type=int, default=4,
                        help="bind worker pool width (with --bind-async)")
    parser.add_argument("--watch-batch-ms", type=float, default=0.0,
                        help="server-side linger per watch poll: trades "
                             "first-event latency for fuller, coalesced "
                             "event batches")
    parser.add_argument("--disable-preemption", action="store_true")
    parser.add_argument("--tenant-quota", action="store_true",
                        help="dominant-resource fair-share chip quotas "
                             "across tenants (pods labeled "
                             "kgtpu.io/tenant): over-share tenants park "
                             "with a typed QuotaExceeded reason at pod-"
                             "pop time and re-admit on chip release; "
                             "weights via config tenantWeights")
    parser.add_argument("--leader-elect", action="store_true",
                        help="active/standby HA: contend for one lease; "
                             "only the holder schedules")
    parser.add_argument("--replicas", type=int, default=1,
                        help="active/active HA: total scheduler replicas "
                             "sharding the queue by pod-name hash "
                             "(optimistic commits, apiserver-arbitrated)")
    parser.add_argument("--shard", type=int, default=0,
                        help="this replica's shard index in [0, replicas)")
    parser.add_argument("--lease-ttl", type=float, default=15.0)
    parser.add_argument("--node-grace-s", type=float, default=0.0,
                        help="heartbeat grace period before a node is "
                             "Lost and its pods (whole gangs) are "
                             "evicted; 0 disables the node lifecycle "
                             "controller. The controller is singleton-"
                             "elected across replicas on its own lease.")
    parser.add_argument("--node-stale-s", type=float, default=0.0,
                        help="heartbeat age marking a node Stale "
                             "(default: node-grace-s / 3)")
    parser.add_argument("--repair", action="store_true",
                        help="device-fault repair controller: migrate "
                             "gangs off degraded chips / dead ICI links "
                             "(checkpoint, evict, requeue) with typed "
                             "parking when no feasible target exists. "
                             "Singleton-elected on its own lease.")
    parser.add_argument("--healthz-port", type=int, default=0,
                        help="healthz + /metrics + /debug/traces + "
                             "/debug/pod/<name> server; 0 disables")
    parser.add_argument("--flight-dir", default=None,
                        help="directory for anomaly flight-recorder "
                             "dumps (internal error, conflict-streak "
                             "escalation, lease loss, gang eviction); "
                             "defaults to $KGTPU_FLIGHT_DIR, unset "
                             "disables")
    parser.add_argument("--scheduler-plugins-dir", default=None,
                        help="load extra device-scheduler plugins (*.py "
                             "exporting create_device_scheduler_plugin)")
    parser.add_argument("--config", default=None,
                        help="JSON/YAML file; explicit flags win")
    common.add_observability_flags(parser)
    args = parser.parse_args(argv)
    config = common.load_config(args.config)
    common.merge_flags(args, config, ["api", "wire", "parallelism",
                                      "lease_ttl",
                                      "node_grace_s", "node_stale_s",
                                      "bind_workers", "watch_batch_ms",
                                      "replicas", "shard"])
    # continuous profiling + metrics time-series (--profile-dir /
    # --metrics-interval-s): started before ANY package object exists so
    # the lock probe wraps every lock the client/scheduler construct —
    # contention is only attributable on locks created after install
    stop_obs = common.start_observability(args)

    # kind-filtered watch: the scheduler consumes node/pod/pv/pvc (and
    # tenant-quota config) events only, so Event records never pay
    # encode/decode on this stream
    client = HTTPAPIClient(args.api,
                           watch_batch_s=args.watch_batch_ms / 1e3,
                           watch_kinds=("node", "pod", "pv", "pvc",
                                        "quota"),
                           wire=args.wire)
    holder = f"{os.uname().nodename}-{os.getpid()}"
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    from kubegpu_tpu import obs

    obs.RECORDER.proc = f"sched-{args.shard}" if args.replicas > 1 \
        else "scheduler"
    if args.flight_dir:
        obs.FLIGHT.configure(args.flight_dir)
    common.serve_health(args.healthz_port, extra_status=lambda: True)
    lifecycle_elector = start_lifecycle_elector(client, args, holder)
    repair_elector = start_repair_elector(client, args, holder)

    if args.replicas > 1:
        # Active/active sharded replicas: build the coordinator first
        # (the scheduler's pop filter reads its ownership), then wire
        # ownership changes to a queue wake-up so stolen pods are
        # retried immediately instead of waiting out their park delay.
        shard = args.shard % args.replicas
        coord = ShardCoordinator(client, shard, args.replicas,
                                 holder, ttl_s=args.lease_ttl)
        sched = build_scheduler(client, args, config,
                                shard_owned=coord.owns,
                                name=f"sched-{shard}")
        coord.on_change = sched.queue.move_all_to_active
        coord.start()
        sched.start()
        print(f"scheduler replica {shard}/{args.replicas} ({holder}) "
              f"running against {args.api}", flush=True)
        stop.wait()
        coord.stop()
        if lifecycle_elector is not None:
            lifecycle_elector.stop()
        if repair_elector is not None:
            repair_elector.stop()
        sched.stop()
        stop_obs()
        return 0

    if not args.leader_elect:
        sched = build_scheduler(client, args, config)
        sched.start()
        print(f"scheduler running against {args.api}", flush=True)
        stop.wait()
        if lifecycle_elector is not None:
            lifecycle_elector.stop()
        if repair_elector is not None:
            repair_elector.stop()
        sched.stop()
        stop_obs()
        return 0

    # Leader election (active/standby) through the shared Elector:
    # acquire -> promote; renew at ttl/3; demote on a real denial or
    # once the lease could have expired (transport-error grace inside
    # Elector.tick — see cluster/lease.py).
    print(f"scheduler candidate {holder} (leader election on)", flush=True)
    state: dict = {"sched": None}

    def promote():
        state["sched"] = build_scheduler(client, args, config)
        state["sched"].start()
        print(f"{holder} became leader", flush=True)

    def demote():
        sched = state.pop("sched", None)
        if sched is not None:
            sched.stop()
        state["sched"] = None
        print(f"{holder} lost the lease, standing by", flush=True)

    elector = Elector(client.acquire_lease, LEASE_NAME, holder,
                      args.lease_ttl, on_acquire=promote, on_lose=demote)
    while not stop.is_set():
        elector.tick()
        stop.wait(args.lease_ttl / 3.0)
    if lifecycle_elector is not None:
        lifecycle_elector.stop()
    if repair_elector is not None:
        repair_elector.stop()
    elector.stop()  # demotes (stops the scheduler) if still leading
    stop_obs()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""``kgtpu-scheduler``: the scheduling engine binary.

Reference: `kube-scheduler/cmd/scheduler.go` + `cmd/app/server.go` —
componentconfig-style ``--config``, healthz/metrics servers, and
lease-based leader election for HA (`server.go:396-403,437-461`): replicas
contend for one lease; only the holder schedules, and a lost lease demotes
the replica back to standby.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
import time

from kubegpu_tpu.cluster.httpapi import HTTPAPIClient
from kubegpu_tpu.cmd import common
from kubegpu_tpu.scheduler.core import Scheduler
from kubegpu_tpu.scheduler.registry import DevicesScheduler
from kubegpu_tpu.scheduler.tpu_scheduler import TPUScheduler

LEASE_NAME = "kgtpu-scheduler"


def build_scheduler(client, args, config: dict | None = None) -> Scheduler:
    from kubegpu_tpu.scheduler.extender import load_extenders
    from kubegpu_tpu.scheduler.factory import algorithm_from_policy

    config = config or {}
    ds = DevicesScheduler()
    ds.add_device(TPUScheduler())
    if getattr(args, "scheduler_plugins_dir", None):
        # the reference's /schedulerplugins seam (`cmd/scheduler.go:50-59`),
        # as a flag instead of a hardcoded path
        ds.add_devices_from_plugins(args.scheduler_plugins_dir)
    # A Policy document (`kube-scheduler/pkg/api/types.go`) recomposes the
    # predicate/priority set by name; inline under "policy" or in its own
    # file via "policyFile". Extenders declared inside the policy merge
    # with top-level ones (upstream puts them in the policy).
    policy = config.get("policy")
    if policy is None and config.get("policyFile"):
        policy = common.load_config(config["policyFile"])
    if policy:
        algorithm = algorithm_from_policy(policy)
    elif config.get("algorithmProvider"):
        from kubegpu_tpu.scheduler.factory import algorithm_provider

        algorithm = algorithm_provider(config["algorithmProvider"])
    else:
        algorithm = None
    extenders = load_extenders(config)
    if policy and policy.get("extenders"):
        extenders += load_extenders({"extenders": policy["extenders"]})
    sched = Scheduler(client, ds, bind_async=bool(args.bind_async),
                      parallelism=args.parallelism,
                      extenders=extenders,
                      priority_weights=config.get("priorityWeights"),
                      algorithm=algorithm,
                      bind_workers=getattr(args, "bind_workers", 4))
    sched.preemption_enabled = not args.disable_preemption
    return sched


def main(argv=None) -> int:
    # Latency-sensitive control loop sharing its process with watch,
    # binder, and fit-pool threads: the default 5 ms GIL switch interval
    # lets any one of them stall the cycle for whole milliseconds.
    import sys

    sys.setswitchinterval(0.0005)
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--api", default="http://127.0.0.1:8070")
    parser.add_argument("--parallelism", type=int, default=16)
    parser.add_argument("--bind-async", action="store_true",
                        help="pipelined binder: the scheduling cycle "
                             "stops at assume and a bounded worker pool "
                             "overlaps the bind round trips")
    parser.add_argument("--bind-workers", type=int, default=4,
                        help="bind worker pool width (with --bind-async)")
    parser.add_argument("--watch-batch-ms", type=float, default=0.0,
                        help="server-side linger per watch poll: trades "
                             "first-event latency for fuller, coalesced "
                             "event batches")
    parser.add_argument("--disable-preemption", action="store_true")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--lease-ttl", type=float, default=15.0)
    parser.add_argument("--node-grace-s", type=float, default=0.0,
                        help="heartbeat grace period before a node is "
                             "Lost and its pods (whole gangs) are "
                             "evicted; 0 disables the node lifecycle "
                             "controller")
    parser.add_argument("--node-stale-s", type=float, default=0.0,
                        help="heartbeat age marking a node Stale "
                             "(default: node-grace-s / 3)")
    parser.add_argument("--healthz-port", type=int, default=0)
    parser.add_argument("--scheduler-plugins-dir", default=None,
                        help="load extra device-scheduler plugins (*.py "
                             "exporting create_device_scheduler_plugin)")
    parser.add_argument("--config", default=None,
                        help="JSON/YAML file; explicit flags win")
    args = parser.parse_args(argv)
    config = common.load_config(args.config)
    common.merge_flags(args, config, ["api", "parallelism", "lease_ttl",
                                      "node_grace_s", "node_stale_s",
                                      "bind_workers", "watch_batch_ms"])

    # kind-filtered watch: the scheduler consumes node/pod/pv/pvc events
    # only, so Event records never pay encode/decode on this stream
    client = HTTPAPIClient(args.api,
                           watch_batch_s=args.watch_batch_ms / 1e3,
                           watch_kinds=("node", "pod", "pv", "pvc"))
    holder = f"{os.uname().nodename}-{os.getpid()}"
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    sched: Scheduler | None = None
    common.serve_health(args.healthz_port,
                        extra_status=lambda: True)

    def start_lifecycle():
        """Node liveness controller, gated on --node-grace-s. Runs only
        while this replica schedules (the leader owns evictions — two
        controllers double-evicting would race the requeues)."""
        if not args.node_grace_s or args.node_grace_s <= 0:
            return None
        from kubegpu_tpu.scheduler.lifecycle import NodeLifecycle

        stale = args.node_stale_s if args.node_stale_s > 0 \
            else args.node_grace_s / 3.0
        controller = NodeLifecycle(client, stale_after_s=stale,
                                   lost_after_s=args.node_grace_s)
        controller.start()
        return controller

    lifecycle = None
    if not args.leader_elect:
        sched = build_scheduler(client, args, config)
        sched.start()
        lifecycle = start_lifecycle()
        print(f"scheduler running against {args.api}", flush=True)
        stop.wait()
        if lifecycle is not None:
            lifecycle.stop()
        sched.stop()
        return 0

    # Leader election: acquire -> run; renew at ttl/3; demote on loss.
    print(f"scheduler candidate {holder} (leader election on)", flush=True)
    leading = False
    lease_valid_until = 0.0
    while not stop.is_set():
        # A transient transport error at renewal must neither crash the
        # replica (the retry layer skips POSTs, and acquire_lease is one)
        # nor demote a leader that still holds the lease: nobody else can
        # acquire until the TTL truly lapses, so tearing down early just
        # leaves the cluster leaderless. Keep leading while the last
        # successful renewal is still within TTL; demote only on a real
        # denial or once the lease could have expired.
        try:
            # stamp validity from BEFORE the round trip: the server's TTL
            # starts when it grants, so counting from the reply would keep
            # us leading ~one RTT past a lapse a standby can already take
            asked_at = time.monotonic()
            acquired = client.acquire_lease(LEASE_NAME, holder,
                                            args.lease_ttl)
            if acquired:
                lease_valid_until = asked_at + args.lease_ttl
        except Exception:
            acquired = leading and time.monotonic() < lease_valid_until
        if acquired and not leading:
            sched = build_scheduler(client, args, config)
            sched.start()
            lifecycle = start_lifecycle()
            leading = True
            print(f"{holder} became leader", flush=True)
        elif not acquired and leading:
            if lifecycle is not None:
                lifecycle.stop()
                lifecycle = None
            sched.stop()
            sched = None
            leading = False
            print(f"{holder} lost the lease, standing by", flush=True)
        stop.wait(args.lease_ttl / 3.0)
    if lifecycle is not None:
        lifecycle.stop()
    if sched is not None:
        sched.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

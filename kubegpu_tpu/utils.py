"""Small helpers shared across layers.

Determinism is the framework's core correctness tool (reference:
`utils/utils.go:34-47`, motivated in `docs/kubegpu.md:24-31`): every map
iteration that feeds an allocation decision must be sorted so that repeated
runs of the scheduler produce identical placements.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping


def sorted_keys(m: Mapping[str, Any]) -> list[str]:
    """Deterministic iteration order for any string-keyed mapping.

    Reference: `utils/utils.go:34-47` (SortedStringKeys).
    """
    return sorted(m.keys())


def assign_nested(d: dict, keys: Iterable[str], value: Any) -> None:
    """Assign ``value`` at the nested path ``keys``, creating dicts on the way.

    Reference: `utils/maputils.go:43-55` (AssignMap), without reflection —
    Python dicts nest naturally.
    """
    keys = list(keys)
    for k in keys[:-1]:
        d = d.setdefault(k, {})
    d[keys[-1]] = value


def get_nested(d: Mapping, keys: Iterable[str], default: Any = None) -> Any:
    """Fetch the value at nested path ``keys`` or ``default`` if absent.

    Reference: `utils/maputils.go:57-68` (GetMap).
    """
    cur: Any = d
    for k in keys:
        if not isinstance(cur, Mapping) or k not in cur:
            return default
        cur = cur[k]
    return cur

"""Small helpers shared across layers.

Determinism is the framework's core correctness tool (reference:
`utils/utils.go:34-47`, motivated in `docs/kubegpu.md:24-31`): every map
iteration that feeds an allocation decision must be sorted so that repeated
runs of the scheduler produce identical placements.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping


def sorted_keys(m: Mapping[str, Any]) -> list[str]:
    """Deterministic iteration order for any string-keyed mapping.

    Reference: `utils/utils.go:34-47` (SortedStringKeys).
    """
    return sorted(m.keys())


def assign_nested(d: dict, keys: Iterable[str], value: Any) -> None:
    """Assign ``value`` at the nested path ``keys``, creating dicts on the way.

    Reference: `utils/maputils.go:43-55` (AssignMap), without reflection —
    Python dicts nest naturally.
    """
    keys = list(keys)
    for k in keys[:-1]:
        d = d.setdefault(k, {})
    d[keys[-1]] = value


def list_bound_pods(api: Any) -> list:
    """Every pod with ``spec.nodeName`` set, via the API server's
    pods-by-node index when the client exposes it (``bound=True``),
    falling back to a full list + filter for older/duck-typed clients —
    the shared read behind eviction sweeps, gang lookups, and
    preemption's victim scan."""
    try:
        return api.list_pods(bound=True)
    except TypeError:
        return [p for p in api.list_pods()
                if (p.get("spec") or {}).get("nodeName")]


def get_nested(d: Mapping, keys: Iterable[str], default: Any = None) -> Any:
    """Fetch the value at nested path ``keys`` or ``default`` if absent.

    Reference: `utils/maputils.go:57-68` (GetMap).
    """
    cur: Any = d
    for k in keys:
        if not isinstance(cur, Mapping) or k not in cur:
            return default
        cur = cur[k]
    return cur

// TPU chip enumeration shim.
//
// The reference reached its native device layer through an external daemon
// (nvidia-docker-plugin wrapping NVML, `nvidia_docker_plugin.go:21-27`);
// the TPU build keeps that seam but implements it natively (SURVEY.md
// §2.9): this library walks an accel-sysfs-style tree (or a fixture tree in
// tests) and emits the host's chip/ICI inventory as JSON, which the Python
// `NativeTPUBackend` parses into a TPUInventory.
//
// Expected tree layout, and how it maps to the PUBLIC TPU-VM layout.
// (This build host has no local accel sysfs — the TPU is behind a
// tunnel — so the layout below is documented against public sources and
// exercised via `write_sysfs_fixture`; the tunnel-reachable device
// attributes are pinned in `tests/fixtures/tpu_device_capture.json`.)
//
// What is standard, with sources:
// - `/sys/class/accel/accel<N>/` per accelerator and `/dev/accel/accel<N>`
//   char devices: the Linux compute-accelerator subsystem
//   (kernel Documentation/accel/introduction.rst, merged v6.2; class
//   name "accel", minors under major 261).
// - Cloud TPU VMs expose one device node per chip, `/dev/accel0..3` on a
//   v4/v5e host (Google Cloud TPU docs, "TPU VM architecture" /
//   troubleshooting pages reference /dev/accel* ownership), and libtpu
//   consumes chip visibility via TPU_VISIBLE_CHIPS-style env — which is
//   exactly what the runtime hook injects (`kubegpu_tpu/runtime/hook.py`).
// - VFIO passthrough hosts instead expose `/dev/vfio/<group>`
//   (kernel Documentation/driver-api/vfio.rst); the optional
//   `vfio_group` attribute models that deployment.
//
// What is THIS framework's contract (not stock kernel attributes):
// `chip_id`, `hbm_bytes`, and the `<root>/topology/` directory are
// populated by the node provisioner (or the test fixture writer,
// `enumerator.write_sysfs_fixture`) from libtpu's topology query — the
// kernel accel class does not publish mesh coordinates or HBM size; some
// runtime component must, and this file defines the agreed shape:
//
//   <root>/accel/accel<N>/device/chip_id     "x.y.z" mesh coordinates
//   <root>/accel/accel<N>/device/hbm_bytes   decimal bytes
//   <root>/accel/accel<N>/device/vfio_group  (optional) vfio group number
//   <root>/topology/mesh_dims                "X,Y,Z"
//   <root>/topology/wrap                     "0|1,0|1,0|1"
//   <root>/topology/host_bounds              "X,Y,Z"
//   <root>/topology/tray_shape               "X,Y,Z"
//   <root>/topology/runtime_version          free-form string
//
// Deviation from the kernel-doc layout: device nodes are emitted flat
// (`/dev/accelN`, the Cloud TPU VM shape) rather than the subsystem's
// `/dev/accel/accelN`; the CRI hook treats both as opaque paths.
//
// C ABI:
//   int tpu_enumerate(const char* root, char* out, int out_len);
//     -> bytes written (JSON), or -1 on error (errno-style via tpu_last_error)
//   const char* tpu_last_error();

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fstream>
#include <sstream>
#include <string>
#include <sys/stat.h>
#include <vector>

namespace {

thread_local std::string g_last_error;

bool read_file(const std::string& path, std::string* out) {
  std::ifstream f(path);
  if (!f.good()) return false;
  std::stringstream ss;
  ss << f.rdbuf();
  *out = ss.str();
  while (!out->empty() && (out->back() == '\n' || out->back() == ' '))
    out->pop_back();
  return true;
}

bool is_dir(const std::string& path) {
  struct stat st;
  return stat(path.c_str(), &st) == 0 && S_ISDIR(st.st_mode);
}

std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

struct Chip {
  int index = -1;
  std::string chip_id;
  long long hbm_bytes = 0;
  std::string vfio_group;  // empty when absent
};

}  // namespace

extern "C" {

const char* tpu_last_error() { return g_last_error.c_str(); }

int tpu_enumerate(const char* root_c, char* out, int out_len) {
  g_last_error.clear();
  const std::string root = root_c ? root_c : "";
  const std::string accel_dir = root + "/accel";
  if (!is_dir(accel_dir)) {
    g_last_error = "no accel directory under " + root;
    return -1;
  }

  // Collect accel<N> entries.
  std::vector<Chip> chips;
  DIR* d = opendir(accel_dir.c_str());
  if (!d) {
    g_last_error = "cannot open " + accel_dir;
    return -1;
  }
  while (dirent* ent = readdir(d)) {
    const std::string name = ent->d_name;
    if (name.rfind("accel", 0) != 0 || name == "accel") continue;
    char* endp = nullptr;
    long idx = strtol(name.c_str() + 5, &endp, 10);
    if (endp == nullptr || *endp != '\0') continue;
    const std::string dev = accel_dir + "/" + name + "/device";
    Chip chip;
    chip.index = static_cast<int>(idx);
    if (!read_file(dev + "/chip_id", &chip.chip_id)) continue;
    std::string hbm;
    if (read_file(dev + "/hbm_bytes", &hbm))
      chip.hbm_bytes = strtoll(hbm.c_str(), nullptr, 10);
    read_file(dev + "/vfio_group", &chip.vfio_group);
    chips.push_back(std::move(chip));
  }
  closedir(d);
  if (chips.empty()) {
    g_last_error = "no chips found under " + accel_dir;
    return -1;
  }
  std::sort(chips.begin(), chips.end(),
            [](const Chip& a, const Chip& b) { return a.index < b.index; });

  auto topo = [&](const char* f, const char* dflt) {
    std::string v;
    if (read_file(root + "/topology/" + f, &v) && !v.empty()) return v;
    return std::string(dflt);
  };

  std::ostringstream js;
  js << "{\"chips\":[";
  for (size_t i = 0; i < chips.size(); i++) {
    const Chip& c = chips[i];
    if (i) js << ",";
    js << "{\"index\":" << c.index
       << ",\"chip_id\":\"" << json_escape(c.chip_id) << "\""
       << ",\"hbm_bytes\":" << c.hbm_bytes
       << ",\"device_paths\":[\"/dev/accel" << c.index << "\"";
    if (!c.vfio_group.empty())
      js << ",\"/dev/vfio/" << json_escape(c.vfio_group) << "\"";
    js << "]}";
  }
  js << "],\"mesh_dims\":[" << topo("mesh_dims", "0,0,0")
     << "],\"wrap\":[" << topo("wrap", "0,0,0")
     << "],\"host_bounds\":[" << topo("host_bounds", "2,2,1")
     << "],\"tray_shape\":[" << topo("tray_shape", "2,1,1")
     << "],\"runtime_version\":\""
     << json_escape(topo("runtime_version", "")) << "\"}";

  const std::string s = js.str();
  if (static_cast<int>(s.size()) + 1 > out_len) {
    g_last_error = "output buffer too small";
    return -1;
  }
  std::memcpy(out, s.c_str(), s.size() + 1);
  return static_cast<int>(s.size());
}

}  // extern "C"

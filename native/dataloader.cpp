// Token-shard data loader: mmap'd shards + a prefetch ring.
//
// The input pipeline for the workload layer (`kubegpu_tpu/workload/data.py`)
// — the "data-loader" entry of the native runtime the reference outsourced
// entirely (its only native seam was the nvidia-docker daemon, SURVEY.md
// §0/§2.9; it has no training runtime at all). Host-side C++ so tokenizing
// IO never competes with the Python thread driving the TPU: a producer
// thread fills a bounded ring of ready batches while the previous step runs
// on device.
//
// Shard format (written by `workload/data.py::write_token_shard`):
//   8-byte magic "KGTDSH01", uint64 LE n_tokens, then n_tokens x uint32 LE.
//
// Sampling contract (MUST stay bit-identical to PyTokenLoader, it is
// differentially tested): splitmix64 PRNG from `seed`; per sample draw
//   r1 = next() -> shard = r1 % n_shards
//   r2 = next() -> start = r2 % (shard_n_tokens - seq1 + 1)
// and emit seq1 consecutive tokens; `batch` samples form one batch, drawn
// in row order. Deterministic across implementations and runs.
//
// C ABI:
//   void* dl_open(const char* paths_nl, long long batch, long long seq1,
//                 unsigned long long seed, int prefetch);
//   long long dl_next(void* h, int* out, long long capacity); // -1 on error
//   void dl_close(void* h);
//   const char* dl_last_error();

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

thread_local std::string g_dl_error;

constexpr char kMagic[8] = {'K', 'G', 'T', 'D', 'S', 'H', '0', '1'};

struct Shard {
  const uint32_t* tokens = nullptr;  // past the header
  uint64_t n_tokens = 0;
  void* map = nullptr;
  size_t map_len = 0;
};

struct SplitMix64 {
  uint64_t x;
  explicit SplitMix64(uint64_t seed) : x(seed) {}
  uint64_t next() {
    x += 0x9E3779B97F4A7C15ULL;
    uint64_t z = x;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
};

struct Loader {
  std::vector<Shard> shards;
  long long batch = 0;
  long long seq1 = 0;
  SplitMix64 rng{0};
  int prefetch = 2;

  std::thread producer;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::deque<std::vector<int32_t>> ring;
  std::atomic<bool> stop{false};
  std::string error;

  ~Loader() {
    stop.store(true);
    cv_space.notify_all();
    cv_ready.notify_all();
    if (producer.joinable()) producer.join();
    for (auto& s : shards)
      if (s.map) munmap(s.map, s.map_len);
  }

  void fill_batch(std::vector<int32_t>* out) {
    out->resize(static_cast<size_t>(batch) * seq1);
    int32_t* dst = out->data();
    for (long long b = 0; b < batch; b++) {
      const uint64_t r1 = rng.next();
      const Shard& s = shards[r1 % shards.size()];
      const uint64_t r2 = rng.next();
      const uint64_t span = s.n_tokens - static_cast<uint64_t>(seq1) + 1;
      const uint64_t start = r2 % span;
      // uint32 tokens -> int32 out (vocab ids are far below 2^31)
      std::memcpy(dst, s.tokens + start,
                  static_cast<size_t>(seq1) * sizeof(int32_t));
      dst += seq1;
    }
  }

  void run() {
    while (!stop.load()) {
      std::vector<int32_t> buf;
      fill_batch(&buf);
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] {
        return stop.load() || static_cast<int>(ring.size()) < prefetch;
      });
      if (stop.load()) return;
      ring.push_back(std::move(buf));
      cv_ready.notify_one();
    }
  }
};

bool open_shard(const std::string& path, Shard* out, std::string* err) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) {
    *err = "cannot open " + path;
    return false;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 16) {
    close(fd);
    *err = "short or unreadable shard " + path;
    return false;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) {
    *err = "mmap failed for " + path;
    return false;
  }
  const char* base = static_cast<const char*>(map);
  if (std::memcmp(base, kMagic, 8) != 0) {
    munmap(map, st.st_size);
    *err = "bad magic in " + path;
    return false;
  }
  uint64_t n_tokens;
  std::memcpy(&n_tokens, base + 8, 8);
  // divide, don't multiply: n_tokens*4 wraps for a corrupted header
  // (n_tokens >= 2^62) and would accept a shard we then read past
  if (n_tokens > (static_cast<uint64_t>(st.st_size) - 16) / 4) {
    munmap(map, st.st_size);
    *err = "truncated shard " + path;
    return false;
  }
  out->map = map;
  out->map_len = st.st_size;
  out->tokens = reinterpret_cast<const uint32_t*>(base + 16);
  out->n_tokens = n_tokens;
  return true;
}

}  // namespace

extern "C" {

const char* dl_last_error() { return g_dl_error.c_str(); }

void* dl_open(const char* paths_nl, long long batch, long long seq1,
              unsigned long long seed, int prefetch) {
  g_dl_error.clear();
  if (!paths_nl || batch <= 0 || seq1 <= 0) {
    g_dl_error = "bad arguments";
    return nullptr;
  }
  auto loader = new Loader();
  loader->batch = batch;
  loader->seq1 = seq1;
  loader->rng = SplitMix64(seed);
  loader->prefetch = prefetch > 0 ? prefetch : 2;

  std::string all(paths_nl), err;
  size_t pos = 0;
  while (pos <= all.size()) {
    size_t nl = all.find('\n', pos);
    std::string path = all.substr(
        pos, nl == std::string::npos ? std::string::npos : nl - pos);
    if (!path.empty()) {
      Shard s;
      if (!open_shard(path, &s, &err)) {
        g_dl_error = err;
        delete loader;
        return nullptr;
      }
      if (s.n_tokens < static_cast<uint64_t>(seq1)) {
        g_dl_error = "shard " + path + " shorter than sequence length";
        munmap(s.map, s.map_len);
        delete loader;
        return nullptr;
      }
      loader->shards.push_back(s);
    }
    if (nl == std::string::npos) break;
    pos = nl + 1;
  }
  if (loader->shards.empty()) {
    g_dl_error = "no shards";
    delete loader;
    return nullptr;
  }
  loader->producer = std::thread([loader] { loader->run(); });
  return loader;
}

long long dl_next(void* h, int32_t* out, long long capacity) {
  g_dl_error.clear();
  auto loader = static_cast<Loader*>(h);
  if (!loader || !out) {
    g_dl_error = "bad handle";
    return -1;
  }
  const long long need = loader->batch * loader->seq1;
  if (capacity < need) {
    g_dl_error = "capacity too small";
    return -1;
  }
  std::vector<int32_t> buf;
  {
    std::unique_lock<std::mutex> lk(loader->mu);
    loader->cv_ready.wait(lk, [&] {
      return loader->stop.load() || !loader->ring.empty();
    });
    if (loader->ring.empty()) {
      g_dl_error = "loader stopped";
      return -1;
    }
    buf = std::move(loader->ring.front());
    loader->ring.pop_front();
    loader->cv_space.notify_one();
  }
  std::memcpy(out, buf.data(), buf.size() * sizeof(int32_t));
  return need;
}

void dl_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"

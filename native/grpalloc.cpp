// Hierarchical group-allocation search: native core.
//
// A 1:1 port of the backtracking search in
// kubegpu_tpu/allocator/grpalloc.py (itself re-implementing the
// reference's device-scheduler/grpalloc/grpallocate.go). The Python
// implementation remains the semantic reference; this core is
// differentially tested against it (tests/test_native.py) and must match
// bit-for-bit: same sorted iteration order (std::map == Python sorted()
// for ASCII paths), same IEEE operation order in the scorers, same
// tie-breaking (>=, prefer-used) in the search.
//
// Wire protocol (line-based, space-separated; resource paths contain no
// whitespace by grammar):
//   in : A <path> <value> <scorer 0=leftover|1=enum>    allocatable
//        U <path> <value>                               node used
//        C <name> <init 0|1> <mode 0=search|1=rescore>  container (in order)
//        R <path> <value> <override -1|0|1>             dev request
//        F <reqpath> <allocpath>                        existing allocate_from
//        E                                              end
//   out: FITS <0|1> / SCORE <%.17g> / C <name> / F <req> <alloc> /
//        REASON <name> <requested> <used> <capacity>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace {

struct ScoreResult {
    bool found;
    double score;
    long long used_cont, new_pod, new_node;
};

// leftover_score (scorers.py:51-70): packing score, init max-not-sum.
ScoreResult leftover_score(long long alloc, long long pod, long long node,
                           const std::vector<long long>& req, bool init) {
    long long total = 0;
    for (long long r : req) total += r;
    long long new_pod = init ? std::max(pod, total) : pod + total;
    long long new_node = node + (new_pod - pod);
    long long left = alloc - new_node;
    double score =
        alloc != 0 ? 1.0 - (static_cast<double>(left) / static_cast<double>(alloc))
                   : 0.0;
    return {left >= 0, score, total, new_pod, new_node};
}

int popcount64(unsigned long long v) {
    int n = 0;
    while (v) { v &= v - 1; ++n; }
    return n;
}

// enum_score (scorers.py:87-104): bitmask attributes, never consumed.
ScoreResult enum_score(long long alloc, long long pod, long long /*node*/,
                       const std::vector<long long>& req, bool /*init*/) {
    long long total = 0;
    for (long long r : req) total |= r;
    long long used_mask = alloc & (pod | total);
    int ba = popcount64(static_cast<unsigned long long>(alloc));
    int bu = popcount64(static_cast<unsigned long long>(used_mask));
    double score = ba ? 1.0 - static_cast<double>(ba - bu) / ba : 0.0;
    bool found = total != 0 ? (alloc & total) != 0 : true;
    return {found, score, total, used_mask, 0};
}

ScoreResult run_scorer(int kind, long long alloc, long long pod, long long node,
                       const std::vector<long long>& req, bool init) {
    return kind == 1 ? enum_score(alloc, pod, node, req, init)
                     : leftover_score(alloc, pod, node, req, init);
}

struct Reason {
    std::string name;
    long long requested = 0, used = 0, capacity = 0;
};

using StrMap = std::map<std::string, std::string>;
using NumMap = std::map<std::string, long long>;

struct Container {
    std::string name;
    bool init = false;
    bool rescore = false;
    NumMap required;                 // global req path -> amount
    std::map<std::string, int> req_scorer;  // override (-1 = none)
    StrMap allocate_from;            // pre-set placements (rescore mode)
};

struct Problem {
    NumMap alloc;
    std::map<std::string, int> alloc_scorer;
    NumMap used;
    std::vector<Container> containers;
};

struct Ctx {
    std::string cont_name;
    bool init = false;
    bool prefer_used = true;
    const NumMap* required = nullptr;
    const std::map<std::string, int>* req_scorer = nullptr;
    const NumMap* alloc = nullptr;
    const std::map<std::string, int>* alloc_scorer = nullptr;
    std::map<std::string, bool>* used_groups = nullptr;
};

// _find_subgroups (grpalloc.py:50-66): split paths as
// base/<name>/<index>/<rest> — name and index are single segments, rest
// may contain '/'. Requires base + at least three further segments.
void find_subgroups(
    const std::string& base, const StrMap& grp,
    std::map<std::string, std::map<std::string, StrMap>>* subgroups,
    std::map<std::string, bool>* is_subgroup) {
    const std::string prefix = base + "/";
    for (const auto& [local_key, global_path] : grp) {
        bool matched = false;
        if (global_path.rfind(prefix, 0) == 0) {
            std::string rest0 = global_path.substr(prefix.size());
            size_t s1 = rest0.find('/');
            if (s1 != std::string::npos) {
                size_t s2 = rest0.find('/', s1 + 1);
                if (s2 != std::string::npos) {
                    std::string name = rest0.substr(0, s1);
                    std::string index = rest0.substr(s1 + 1, s2 - s1 - 1);
                    std::string rest = rest0.substr(s2 + 1);
                    (*subgroups)[name][index][rest] = global_path;
                    matched = true;
                }
            }
        }
        (*is_subgroup)[local_key] = matched;
    }
}

// _GrpAllocator (grpalloc.py:92-315): one level of the recursive search.
// Mutable state has value semantics — copying the struct IS _clone().
struct Grp {
    Ctx* ctx;
    const StrMap* grp_required;                    // local -> global req
    const std::map<std::string, StrMap>* grp_alloc;  // location -> local -> global
    std::string req_base, alloc_base_prefix;
    StrMap allocate_from;
    NumMap pod_res, node_res;
    double score = 0.0;
    std::map<std::string, bool> is_req_subgrp;

    void take(Grp&& other) {
        allocate_from = std::move(other.allocate_from);
        pod_res = std::move(other.pod_res);
        node_res = std::move(other.node_res);
        score = other.score;
    }

    // _resource_available (grpalloc.py:141-175)
    bool resource_available(const std::string& location,
                            std::vector<Reason>* fails) {
        static const StrMap kEmpty;
        auto it = grp_alloc->find(location);
        const StrMap& loc_alloc = it == grp_alloc->end() ? kEmpty : it->second;
        bool found = true;
        for (const auto& [req_key, req_global] : *grp_required) {
            auto sub_it = is_req_subgrp.find(req_key);
            if (sub_it != is_req_subgrp.end() && sub_it->second) continue;
            long long required = 0;
            auto rit = ctx->required->find(req_global);
            if (rit != ctx->required->end()) required = rit->second;
            auto lit = loc_alloc.find(req_key);
            if (lit == loc_alloc.end()) {
                found = false;
                fails->push_back({ctx->cont_name + "/" + req_global,
                                  required, 0, 0});
                continue;
            }
            const std::string& global_name = lit->second;
            int kind = -1;
            auto oit = ctx->req_scorer->find(req_global);
            if (oit != ctx->req_scorer->end() && oit->second >= 0)
                kind = oit->second;
            if (kind < 0) kind = ctx->alloc_scorer->at(global_name);
            long long allocatable = ctx->alloc->at(global_name);
            long long used_node = 0, used_pod = 0;
            auto nit = node_res.find(global_name);
            if (nit != node_res.end()) used_node = nit->second;
            auto pit = pod_res.find(global_name);
            if (pit != pod_res.end()) used_pod = pit->second;
            ScoreResult r = run_scorer(kind, allocatable, used_pod, used_node,
                                       {required}, ctx->init);
            if (!r.found) {
                found = false;
                fails->push_back({ctx->cont_name + "/" + req_global,
                                  required, used_node, allocatable});
                continue;
            }
            pod_res[global_name] = r.new_pod;
            node_res[global_name] = r.new_node;
            allocate_from[req_global] = global_name;
        }
        return found;
    }

    // _allocate_subgroups (grpalloc.py:177-203)
    bool allocate_subgroups(
        const std::string& location,
        const std::map<std::string, std::map<std::string, StrMap>>& subgrps_req,
        const std::map<std::string, std::map<std::string, StrMap>>& subgrps_alloc,
        std::vector<Reason>* fails) {
        bool found = true;
        for (const auto& [name, by_index] : subgrps_req) {
            static const std::map<std::string, StrMap> kEmptyAlloc;
            auto ait = subgrps_alloc.find(name);
            const std::map<std::string, StrMap>& sub_alloc =
                ait == subgrps_alloc.end() ? kEmptyAlloc : ait->second;
            for (const auto& [index, req_map] : by_index) {
                Grp sub{ctx,
                        &req_map,
                        &sub_alloc,
                        req_base + "/" + name + "/" + index,
                        alloc_base_prefix + "/" + location + "/" + name,
                        allocate_from,
                        pod_res,
                        node_res,
                        0.0,
                        {}};
                std::vector<Reason> reasons;
                bool ok = sub.allocate_group(&reasons);
                if (!ok) {
                    found = false;
                    fails->push_back({ctx->cont_name + "/" + sub.req_base,
                                      0, 0, 0});
                    fails->insert(fails->end(), reasons.begin(), reasons.end());
                    continue;
                }
                take(std::move(sub));
            }
        }
        return found;
    }

    // _find_score_and_update (grpalloc.py:205-245)
    bool find_score_and_update(const std::string& location,
                               std::vector<Reason>* fails) {
        bool found = true;
        std::map<std::string, std::vector<long long>> requested;
        for (const auto& [req_key, req_global] : *grp_required) {
            (void)req_key;
            std::string alloc_from;
            auto ait = allocate_from.find(req_global);
            if (ait != allocate_from.end()) alloc_from = ait->second;
            long long required = 0;
            auto rit = ctx->required->find(req_global);
            if (rit != ctx->required->end()) required = rit->second;
            if (ctx->alloc->find(alloc_from) == ctx->alloc->end()) {
                found = false;
                fails->push_back({req_global, required, 0, 0});
                continue;
            }
            requested[alloc_from].push_back(required);
        }
        score = 0.0;
        static const StrMap kEmpty;
        auto lit = grp_alloc->find(location);
        const StrMap& loc_resources = lit == grp_alloc->end() ? kEmpty : lit->second;
        for (const auto& [key, global_name] : loc_resources) {
            (void)key;
            long long allocatable = ctx->alloc->at(global_name);
            int kind = ctx->alloc_scorer->at(global_name);
            long long used_node = 0, used_pod = 0;
            auto nit = node_res.find(global_name);
            if (nit != node_res.end()) used_node = nit->second;
            auto pit = pod_res.find(global_name);
            if (pit != pod_res.end()) used_pod = pit->second;
            static const std::vector<long long> kNone;
            auto qit = requested.find(global_name);
            const std::vector<long long>& reqs =
                qit == requested.end() ? kNone : qit->second;
            ScoreResult r = run_scorer(kind, allocatable, used_pod, used_node,
                                       reqs, ctx->init);
            if (!r.found) {
                found = false;
                fails->push_back({global_name, r.used_cont, used_node,
                                  allocatable});
                continue;
            }
            score += r.score;
            pod_res[global_name] = r.new_pod;
            node_res[global_name] = r.new_node;
        }
        if (!loc_resources.empty())
            score /= static_cast<double>(loc_resources.size());
        return found;
    }

    // _allocate_group_at (grpalloc.py:247-267)
    bool allocate_group_at(
        const std::string& location,
        const std::map<std::string, std::map<std::string, StrMap>>& subgrps_req,
        std::vector<Reason>* fails) {
        std::string location_name = alloc_base_prefix + "/" + location;
        static const StrMap kEmpty;
        auto lit = grp_alloc->find(location);
        const StrMap& loc_resources = lit == grp_alloc->end() ? kEmpty : lit->second;
        std::map<std::string, std::map<std::string, StrMap>> subgrps_alloc;
        std::map<std::string, bool> ignore;
        find_subgroups(location_name, loc_resources, &subgrps_alloc, &ignore);

        // saved copies for the reset discipline (clone -> charge -> reset)
        NumMap saved_pod = pod_res, saved_node = node_res;
        double saved_score = score;
        bool found_res = resource_available(location, fails);
        std::vector<Reason> fails_next;
        bool found_next =
            allocate_subgroups(location, subgrps_req, subgrps_alloc, &fails_next);
        if (found_res && found_next) {
            pod_res = std::move(saved_pod);
            node_res = std::move(saved_node);
            score = saved_score;
            std::vector<Reason> fails_score;
            bool found_score = find_score_and_update(location, &fails_score);
            if (!found_score) {
                found_next = false;
                fails_next.insert(fails_next.end(), fails_score.begin(),
                                  fails_score.end());
            }
        }
        fails->insert(fails->end(), fails_next.begin(), fails_next.end());
        return found_res && found_next;
    }

    // allocate_group (grpalloc.py:269-315): branch-and-keep-best.
    bool allocate_group(std::vector<Reason>* fails) {
        if (grp_required->empty()) return true;

        std::map<std::string, std::map<std::string, StrMap>> subgrps_req;
        is_req_subgrp.clear();
        find_subgroups(req_base, *grp_required, &subgrps_req, &is_req_subgrp);

        bool have_best = false;
        Grp best{};
        double best_score = score;
        bool best_is_used = false;
        std::string best_name;
        bool any_find = false;

        for (const auto& [location, unused] : *grp_alloc) {
            (void)unused;
            Grp cand = *this;  // _clone()
            std::vector<Reason> reasons;
            bool found = cand.allocate_group_at(location, subgrps_req, &reasons);
            std::string location_name = alloc_base_prefix + "/" + location;
            if (found) {
                bool cand_is_used = false;
                auto uit = ctx->used_groups->find(location_name);
                if (uit != ctx->used_groups->end()) cand_is_used = uit->second;
                bool take_new;
                if (!ctx->prefer_used)
                    take_new = cand.score >= best_score;
                else if (best_is_used)
                    take_new = cand_is_used && cand.score >= best_score;
                else
                    take_new = cand_is_used || cand.score >= best_score;
                if (take_new) {
                    any_find = true;
                    have_best = true;
                    best = std::move(cand);
                    best_score = best.score;
                    best_is_used = cand_is_used;
                    best_name = location_name;
                }
            } else if (grp_alloc->size() == 1) {
                fails->insert(fails->end(), reasons.begin(), reasons.end());
            }
        }
        if (have_best) take(std::move(best));
        if (any_find) {
            (*ctx->used_groups)[best_name] = true;
            return true;
        }
        return false;
    }
};

// _container_fits_group_constraints + pod_fits_group_constraints
// (grpalloc.py:318-423)
struct Output {
    bool fits = true;
    double score = 0.0;
    std::vector<Reason> reasons;
    std::vector<std::pair<std::string, StrMap>> allocations;  // per container
};

Output solve(const Problem& prob) {
    Output out;
    NumMap pod_res;
    NumMap node_res = prob.used;
    std::map<std::string, bool> used_groups;

    const std::string kPrefix = "alpha/grpresource";
    std::string grp_prefix = "alpha";
    std::string grp_name = "grpresource";

    for (const auto& cont : prob.containers) {
        StrMap top_location;
        for (const auto& [res, val] : prob.alloc) {
            (void)val;
            top_location[res] = res;
        }
        StrMap grp_required;
        for (const auto& [res, val] : cont.required) {
            (void)val;
            grp_required[res] = res;
        }
        std::map<std::string, StrMap> grp_alloc;
        grp_alloc[grp_name] = std::move(top_location);

        Ctx ctx;
        ctx.cont_name = cont.name;
        ctx.init = cont.init;
        ctx.prefer_used = true;
        ctx.required = &cont.required;
        ctx.req_scorer = &cont.req_scorer;
        ctx.alloc = &prob.alloc;
        ctx.alloc_scorer = &prob.alloc_scorer;
        ctx.used_groups = &used_groups;

        Grp grp{&ctx,    &grp_required, &grp_alloc, kPrefix,
                grp_prefix, {},          pod_res,    node_res,
                0.0,     {}};

        std::vector<Reason> reasons;
        bool found;
        if (!cont.rescore) {
            found = grp.allocate_group(&reasons);
        } else {
            grp.allocate_from = cont.allocate_from;
            found = grp.find_score_and_update(grp_name, &reasons);
        }
        if (!found) {
            out.fits = false;
            out.reasons.insert(out.reasons.end(), reasons.begin(),
                               reasons.end());
        } else if (!cont.init) {
            out.score = grp.score;
        }
        if (!cont.rescore)
            out.allocations.emplace_back(cont.name, grp.allocate_from);
        pod_res = std::move(grp.pod_res);
        node_res = std::move(grp.node_res);
    }
    return out;
}

thread_local std::string g_grp_error;

}  // namespace

extern "C" int grp_allocate(const char* input, char* out_buf, int out_cap) {
    Problem prob;
    std::istringstream in(input);
    std::string line;
    Container* cur = nullptr;
    while (std::getline(in, line)) {
        if (line.empty()) continue;
        std::istringstream ls(line);
        std::string tag;
        ls >> tag;
        if (tag == "A") {
            std::string path; long long val = 0; int sc = 0;
            ls >> path >> val >> sc;
            prob.alloc[path] = val;
            prob.alloc_scorer[path] = sc;
        } else if (tag == "U") {
            std::string path; long long val = 0;
            ls >> path >> val;
            prob.used[path] = val;
        } else if (tag == "C") {
            prob.containers.emplace_back();
            cur = &prob.containers.back();
            int init = 0, mode = 0;
            ls >> cur->name >> init >> mode;
            cur->init = init != 0;
            cur->rescore = mode != 0;
        } else if (tag == "R") {
            if (!cur) { g_grp_error = "R before C"; return -1; }
            std::string path; long long val = 0; int ov = -1;
            ls >> path >> val >> ov;
            cur->required[path] = val;
            cur->req_scorer[path] = ov;
        } else if (tag == "F") {
            if (!cur) { g_grp_error = "F before C"; return -1; }
            std::string req, alloc;
            ls >> req >> alloc;
            cur->allocate_from[req] = alloc;
        } else if (tag == "E") {
            break;
        } else {
            g_grp_error = "unknown tag: " + tag;
            return -1;
        }
        if (ls.fail()) { g_grp_error = "parse error: " + line; return -1; }
    }

    Output result = solve(prob);

    std::ostringstream os;
    os << "FITS " << (result.fits ? 1 : 0) << "\n";
    char fbuf[64];
    std::snprintf(fbuf, sizeof(fbuf), "%.17g", result.score);
    os << "SCORE " << fbuf << "\n";
    for (const auto& [name, af] : result.allocations) {
        os << "C " << name << "\n";
        for (const auto& [req, alloc] : af)
            os << "F " << req << " " << alloc << "\n";
    }
    for (const auto& r : result.reasons)
        os << "REASON " << r.name << " " << r.requested << " " << r.used
           << " " << r.capacity << "\n";
    std::string s = os.str();
    if (static_cast<int>(s.size()) + 1 > out_cap) {
        g_grp_error = "output buffer too small";
        return -2;
    }
    std::memcpy(out_buf, s.c_str(), s.size() + 1);
    return static_cast<int>(s.size());
}

extern "C" const char* grp_last_error() { return g_grp_error.c_str(); }

// Contiguous sub-mesh search — native core.
//
// Bit-identical port of `kubegpu_tpu/topology/mesh.py::find_contiguous_block`
// (same shape ordering, same exposure/origin tie-breaking, same greedy
// fallback), for the gang-scheduling hot path on large slices where the
// Python search dominates planning time. The Python implementation remains
// the semantic reference; tests diff the two over randomized cases.
//
// C ABI:
//   int tpu_find_contiguous_block(const int dims[3], const int wrap[3],
//                                 const int* free_xyz, int n_free,
//                                 int count, int* out_xyz);
//     -> number of coords written (== count), or -1 when no connected
//        subset of that size exists. count<=0 -> 0.

#include <algorithm>
#include <array>
#include <cstring>
#include <map>
#include <set>
#include <vector>

namespace {

using Coord = std::array<int, 3>;

const int kDirs[6][3] = {
    {1, 0, 0}, {-1, 0, 0}, {0, 1, 0}, {0, -1, 0}, {0, 0, 1}, {0, 0, -1},
};

struct MeshCtx {
  int dims[3];
  bool wrap[3];

  bool neighbor(const Coord& c, const int* d, Coord* out) const {
    Coord n;
    for (int i = 0; i < 3; i++) {
      int v = c[i] + d[i];
      if (wrap[i]) {
        v = ((v % dims[i]) + dims[i]) % dims[i];
      } else if (v < 0 || v >= dims[i]) {
        return false;
      }
      n[i] = v;
    }
    if (n == c) return false;  // dim-1 wrap self-link
    *out = n;
    return true;
  }
};

// Axis-aligned box shapes of volume `count`, most compact first — mirrors
// `_block_shapes` (sort key: surface area, then the shape tuple).
std::vector<Coord> block_shapes(int count) {
  std::set<Coord> shapes;
  for (int a = 1; a <= count; a++) {
    if (count % a) continue;
    int rest = count / a;
    for (int b = 1; b <= rest; b++) {
      if (rest % b) continue;
      int c = rest / b;
      Coord s = {a, b, c};
      std::sort(s.begin(), s.end());
      do {
        shapes.insert(s);
      } while (std::next_permutation(s.begin(), s.end()));
    }
  }
  std::vector<Coord> out(shapes.begin(), shapes.end());
  std::stable_sort(out.begin(), out.end(), [](const Coord& x, const Coord& y) {
    long sx = (long)x[0] * x[1] + (long)x[1] * x[2] + (long)x[0] * x[2];
    long sy = (long)y[0] * y[1] + (long)y[1] * y[2] + (long)y[0] * y[2];
    if (sx != sy) return sx < sy;
    return x < y;
  });
  return out;
}

// Coords of the box at `origin`; false if it leaves the mesh or wraps onto
// itself — mirrors `_block_coords`.
bool box_at(const Coord& origin, const Coord& shape, const MeshCtx& mesh,
            std::vector<Coord>* out) {
  out->clear();
  for (int dx = 0; dx < shape[0]; dx++)
    for (int dy = 0; dy < shape[1]; dy++)
      for (int dz = 0; dz < shape[2]; dz++) {
        Coord c;
        const int d[3] = {dx, dy, dz};
        for (int i = 0; i < 3; i++) {
          int v = origin[i] + d[i];
          if (v >= mesh.dims[i]) {
            if (!mesh.wrap[i]) return false;
            v %= mesh.dims[i];
          }
          c[i] = v;
        }
        out->push_back(c);
      }
  std::set<Coord> uniq(out->begin(), out->end());
  return uniq.size() == out->size();
}

int exposure(const std::vector<Coord>& block, const std::set<Coord>& free,
             const MeshCtx& mesh) {
  std::set<Coord> blockset(block.begin(), block.end());
  std::set<Coord> seen;
  for (const Coord& c : block)
    for (const auto& d : kDirs) {
      Coord n;
      if (mesh.neighbor(c, d, &n) && free.count(n) && !blockset.count(n))
        seen.insert(n);
    }
  return (int)seen.size();
}

// Connected components of the free set, largest first (ties: smallest
// member) — mirrors `free_components`.
std::vector<std::set<Coord>> components(const std::set<Coord>& free_in,
                                        const MeshCtx& mesh) {
  std::set<Coord> free = free_in;
  std::vector<std::set<Coord>> comps;
  while (!free.empty()) {
    std::set<Coord> comp;
    std::vector<Coord> stack = {*free.begin()};
    while (!stack.empty()) {
      Coord c = stack.back();
      stack.pop_back();
      if (!free.count(c) || comp.count(c)) continue;
      comp.insert(c);
      for (const auto& d : kDirs) {
        Coord n;
        if (mesh.neighbor(c, d, &n) && free.count(n) && !comp.count(n))
          stack.push_back(n);
      }
    }
    for (const Coord& c : comp) free.erase(c);
    comps.push_back(std::move(comp));
  }
  std::stable_sort(comps.begin(), comps.end(),
                   [](const std::set<Coord>& a, const std::set<Coord>& b) {
                     if (a.size() != b.size()) return a.size() > b.size();
                     return *a.begin() < *b.begin();
                   });
  return comps;
}

}  // namespace

extern "C" int tpu_find_contiguous_block(const int* dims, const int* wrap,
                                         const int* free_xyz, int n_free,
                                         int count, int* out_xyz) {
  if (count <= 0) return 0;
  MeshCtx mesh;
  for (int i = 0; i < 3; i++) {
    mesh.dims[i] = dims[i];
    mesh.wrap[i] = wrap[i] != 0;
  }
  std::set<Coord> free;
  for (int i = 0; i < n_free; i++)
    free.insert({free_xyz[3 * i], free_xyz[3 * i + 1], free_xyz[3 * i + 2]});
  if ((int)free.size() < count) return -1;

  auto emit = [&](std::vector<Coord> block) {
    std::sort(block.begin(), block.end());
    for (int i = 0; i < (int)block.size(); i++)
      for (int j = 0; j < 3; j++) out_xyz[3 * i + j] = block[i][j];
    return (int)block.size();
  };

  // Pass 1: compact axis-aligned boxes, least-exposure placement.
  for (const Coord& shape : block_shapes(count)) {
    bool fits_dims = true;
    for (int i = 0; i < 3; i++)
      if (shape[i] > mesh.dims[i]) fits_dims = false;
    if (!fits_dims) continue;
    bool have_best = false;
    std::pair<int, Coord> best_key;
    std::vector<Coord> best_block, block;
    for (const Coord& origin : free) {  // std::set iterates sorted
      if (!box_at(origin, shape, mesh, &block)) continue;
      bool subset = true;
      for (const Coord& c : block)
        if (!free.count(c)) {
          subset = false;
          break;
        }
      if (!subset) continue;
      std::pair<int, Coord> key = {exposure(block, free, mesh), origin};
      if (!have_best || key < best_key) {
        have_best = true;
        best_key = key;
        best_block = block;
      }
    }
    if (have_best) return emit(best_block);
  }

  // Pass 2: greedy compact connected growth inside each component.
  for (const auto& comp : components(free, mesh)) {
    if ((int)comp.size() < count) continue;
    Coord seed = *comp.begin();
    std::vector<Coord> selected = {seed};
    std::set<Coord> selset = {seed};
    while ((int)selected.size() < count) {
      std::map<Coord, int> frontier;  // sorted by coord
      for (const Coord& c : selected)
        for (const auto& d : kDirs) {
          Coord n;
          if (mesh.neighbor(c, d, &n) && comp.count(n) && !selset.count(n))
            frontier[n]++;
        }
      if (frontier.empty()) break;
      // Python: max(sorted(frontier), key=count) -> first maximal in
      // ascending coord order == smallest coord with the max count.
      Coord next = frontier.begin()->first;
      int best = frontier.begin()->second;
      for (const auto& kv : frontier)
        if (kv.second > best) {
          best = kv.second;
          next = kv.first;
        }
      selected.push_back(next);
      selset.insert(next);
    }
    if ((int)selected.size() == count) return emit(selected);
  }
  return -1;
}

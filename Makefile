# Developer / CI entry points. `make check` is the gate CI runs
# (.github/workflows/ci.yml); every target also works standalone.
#
# ruff and mypy are OPTIONAL layers: environments without them (the
# hermetic test container) skip those layers with a notice instead of
# failing — the project-native analyzer and the test suite always run.

PY ?= python

.PHONY: check analyze lint type test rules report mutate mutate-smoke

check: analyze lint type test

# project-native invariants: lock discipline, monotonic clocks, codec
# pairing, swallowed exceptions, metric registry, charge pairing,
# resource lifecycle, wire contracts, interprocedural lockset races,
# hot-path purity contracts (exit 1 on findings; exit 3 when the
# dataflow pass blows the wall-clock budget — a perf regression in
# the analyzer itself is a finding too)
analyze:
	$(PY) -m kubegpu_tpu.analysis --stats --budget-s 120 kubegpu_tpu

# the ranked inventories: hot-path's vectorization blockers and
# host-sync's syncs-per-loop-iteration worklist (the serving rewrite's
# blocker list — rank 1 is the loop paying the most dispatch RTTs
# per token)
report:
	$(PY) -m kubegpu_tpu.analysis --rule hot-path --report kubegpu_tpu
	$(PY) -m kubegpu_tpu.analysis --rule host-sync --report kubegpu_tpu

# the dynamic half of the dual-path drift defense: AST mutants over
# the vector/scalar twin closure, each killed by the differential
# suite or carrying a justified equivalent-mutant waiver (exit 1 on
# unwaived survivors). `mutate-smoke` is CI's fast PR-time subset.
mutate:
	$(PY) -m kubegpu_tpu.analysis --mutate

mutate-smoke:
	$(PY) -m kubegpu_tpu.analysis --mutate --mutate-smoke

rules:
	$(PY) -m kubegpu_tpu.analysis --list-rules

lint:
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check kubegpu_tpu tests; \
	else \
		echo "lint: ruff not installed; skipping (pip install ruff)"; \
	fi

type:
	@if $(PY) -m mypy --version >/dev/null 2>&1; then \
		$(PY) -m mypy; \
	else \
		echo "type: mypy not installed; skipping (pip install mypy)"; \
	fi

# tier-1: the suite runs under the lock-order harness (a lock-order
# inversion observed anywhere fails the run)
test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider
